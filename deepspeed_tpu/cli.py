"""CLI entry points (ref: bin/deepspeed, bin/ds_report, bin/ds_elastic).

Usable as modules (no install step needed):
    python -m deepspeed_tpu.launcher.runner train.py -- args...
    python -m deepspeed_tpu.env_report
    python -m deepspeed_tpu.cli elastic --config ds_config.json [-w WORLD]
    python -m deepspeed_tpu.cli ssh -H hostfile -- nvidia-smi-equivalent
"""

import argparse
import json
import sys


def ds_elastic_main(argv=None):
    """(ref: bin/ds_elastic) print elastic batch + valid chip counts."""
    parser = argparse.ArgumentParser(prog="ds_elastic")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args(argv)

    from deepspeed_tpu.elasticity import compute_elastic_config
    from deepspeed_tpu.version import __version__

    with open(args.config) as f:
        ds_config = json.load(f)
    print(json.dumps(ds_config.get("elasticity", {}), indent=2))
    if args.world_size > 0:
        final, valid, micro = compute_elastic_config(
            ds_config, __version__, world_size=args.world_size)
        print(f"With world size {args.world_size}:")
        print(f"  final global batch size .... {final}")
        print(f"  valid chip counts .......... {valid}")
        print(f"  micro batch per chip ....... {micro}")
    else:
        final, valid = compute_elastic_config(ds_config, __version__)
        print(f"final global batch size .... {final}")
        print(f"valid chip counts .......... {valid}")


def zero_to_fp32_main(argv=None):
    """(ref: deepspeed/utils/zero_to_fp32.py) consolidate a sharded
    checkpoint into one fp32 .npz of full weights."""
    parser = argparse.ArgumentParser(prog="zero_to_fp32")
    parser.add_argument("checkpoint_dir",
                        help="dir containing the 'latest' tag file")
    parser.add_argument("output_file", help="output .npz path")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args(argv)

    from deepspeed_tpu.runtime.checkpointing import (
        load_fp32_state_dict_from_zero_checkpoint)
    from deepspeed_tpu.utils.tree import tree_path_str
    import jax.tree_util as jtu
    import numpy as np

    params = load_fp32_state_dict_from_zero_checkpoint(
        args.checkpoint_dir, tag=args.tag)
    flat = {}
    for path, leaf in jtu.tree_flatten_with_path(params)[0]:
        flat[tree_path_str(path)] = np.asarray(leaf, np.float32)
    np.savez(args.output_file, **flat)
    total = sum(v.size for v in flat.values())
    print(f"saved {len(flat)} tensors / {total / 1e6:.2f}M params "
          f"to {args.output_file}")


def ds_ssh_main(argv=None):
    """(ref: bin/ds_ssh) run a command on every hostfile node, in
    parallel, with per-host-prefixed output. Exit code is the worst
    per-host code, so scripts can gate on cluster-wide success."""
    parser = argparse.ArgumentParser(prog="ds_ssh")
    parser.add_argument("-H", "--hostfile", default="/job/hostfile")
    parser.add_argument("--ssh-cmd", default="ssh",
                        help="transport binary (tests point this at a "
                             "stub; gcloud users at their ssh wrapper)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every node")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    # drop only the leading separator: a later literal "--" belongs to
    # the remote command itself
    cmd = list(args.command)
    if "--" in cmd:
        cmd.remove("--")

    import subprocess

    from deepspeed_tpu.launcher.runner import fetch_hostfile
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        print(f"ds_ssh: no hostfile at {args.hostfile}", file=sys.stderr)
        sys.exit(2)
    procs = {h: subprocess.Popen([args.ssh_cmd, h] + cmd,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
             for h in pool}
    worst = 0
    for h, p in procs.items():
        out, _ = p.communicate()
        for line in (out or "").splitlines():
            print(f"[{h}] {line}")
        if p.returncode:
            print(f"[{h}] exit {p.returncode}", file=sys.stderr)
        # signal-killed ssh gives a NEGATIVE returncode; abs() keeps it
        # from comparing below 0 and reporting success
        worst = max(worst, abs(p.returncode))
    sys.exit(worst)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return
    cmd, rest = argv[0], argv[1:]
    if cmd == "elastic":
        ds_elastic_main(rest)
    elif cmd == "ssh":
        ds_ssh_main(rest)
    elif cmd == "zero_to_fp32":
        zero_to_fp32_main(rest)
    elif cmd == "report":
        from deepspeed_tpu.env_report import main as report_main
        report_main()
    elif cmd == "launch":
        from deepspeed_tpu.launcher.runner import main as runner_main
        runner_main(rest)
    else:
        print(__doc__)
        sys.exit(2)


if __name__ == "__main__":
    main()
