"""CLI entry points (ref: bin/deepspeed, bin/ds_report, bin/ds_elastic).

Usable as modules (no install step needed):
    python -m deepspeed_tpu.launcher.runner train.py -- args...
    python -m deepspeed_tpu.env_report
    python -m deepspeed_tpu.cli elastic --config ds_config.json [-w WORLD]
"""

import argparse
import json
import sys


def ds_elastic_main(argv=None):
    """(ref: bin/ds_elastic) print elastic batch + valid chip counts."""
    parser = argparse.ArgumentParser(prog="ds_elastic")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args(argv)

    from deepspeed_tpu.elasticity import compute_elastic_config
    from deepspeed_tpu.version import __version__

    with open(args.config) as f:
        ds_config = json.load(f)
    print(json.dumps(ds_config.get("elasticity", {}), indent=2))
    if args.world_size > 0:
        final, valid, micro = compute_elastic_config(
            ds_config, __version__, world_size=args.world_size)
        print(f"With world size {args.world_size}:")
        print(f"  final global batch size .... {final}")
        print(f"  valid chip counts .......... {valid}")
        print(f"  micro batch per chip ....... {micro}")
    else:
        final, valid = compute_elastic_config(ds_config, __version__)
        print(f"final global batch size .... {final}")
        print(f"valid chip counts .......... {valid}")


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return
    cmd, rest = argv[0], argv[1:]
    if cmd == "elastic":
        ds_elastic_main(rest)
    elif cmd == "report":
        from deepspeed_tpu.env_report import main as report_main
        report_main()
    elif cmd == "launch":
        from deepspeed_tpu.launcher.runner import main as runner_main
        runner_main(rest)
    else:
        print(__doc__)
        sys.exit(2)


if __name__ == "__main__":
    main()
