from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler

__all__ = ["CurriculumScheduler"]
