"""Curriculum learning: step-indexed difficulty schedule (seqlen).

Capability match for the reference's ``CurriculumScheduler``
(ref: deepspeed/runtime/data_pipeline/curriculum_scheduler.py:8) with the
same three schedule types — ``fixed_discrete``, ``fixed_linear``,
``fixed_root`` — and the same state dict for checkpointing.

TPU note: the reference injects ``curriculum_seqlen`` as a forward
kwarg; here the engine *truncates the batch's sequence axis* before the
jitted step instead. Each distinct difficulty value is a distinct XLA
program, so ``difficulty_step`` (multiples of 8/16 for Tensor Cores in
the reference) doubles as the recompile throttle on TPU — and keeps the
sequence dim friendly to the 128-lane layout.
"""

import math
from typing import Any, Dict

from deepspeed_tpu.utils.logging import logger

FIXED_DISCRETE = "fixed_discrete"
FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        for key in ("curriculum_type", "min_difficulty", "max_difficulty",
                    "schedule_type"):
            assert key in config, \
                f"Curriculum learning requires the config '{key}'"
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        self.state["schedule_type"] = config["schedule_type"]
        schedule_config = config.get("schedule_config", {})
        stype = config["schedule_type"]

        if stype == FIXED_DISCRETE:
            # difficulty list + max_step list (one shorter; last difficulty
            # holds for all following steps), ref :22-40
            assert "difficulty" in schedule_config
            assert "max_step" in schedule_config
            assert len(schedule_config["max_step"]) > 0
            assert len(schedule_config["difficulty"]) == \
                len(schedule_config["max_step"]) + 1
            self.state["schedule"] = schedule_config
        elif stype in (FIXED_ROOT, FIXED_LINEAR):
            assert "total_curriculum_step" in schedule_config
            assert "difficulty_step" in schedule_config
            if stype == FIXED_ROOT:
                assert "root_degree" in schedule_config
            if schedule_config["difficulty_step"] % 8 != 0:
                logger.warning(
                    "difficulty_step that is a multiple of 8 keeps the "
                    "sequence dimension aligned to the TPU lane layout; "
                    "other values may pad/recompile inefficiently.")
            self.state["schedule"] = schedule_config
        else:
            raise RuntimeError("Unsupported curriculum schedule type")

    # -- reference API -------------------------------------------------

    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def get_state(self) -> Dict[str, Any]:
        return self.state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.state = state

    def _fixed_discrete(self, global_steps: int) -> int:
        s = self.state["schedule"]
        if global_steps > s["max_step"][-1]:
            return s["difficulty"][-1]
        for i, mstep in enumerate(s["max_step"]):
            if global_steps <= mstep:
                return s["difficulty"][i]
        return s["difficulty"][-1]

    def _fixed_root(self, global_steps: int, root_degree=None) -> int:
        s = self.state["schedule"]
        if root_degree is None:
            root_degree = s["root_degree"]
        frac = (float(global_steps) / s["total_curriculum_step"]) \
            ** (1.0 / root_degree)
        next_difficulty = math.floor(
            frac * (self.state["max_difficulty"] - self.state["min_difficulty"])
            + self.state["min_difficulty"])
        next_difficulty -= next_difficulty % s["difficulty_step"]
        return min(next_difficulty, self.state["max_difficulty"])

    def get_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == FIXED_DISCRETE:
            return self._fixed_discrete(global_steps)
        if stype == FIXED_LINEAR:
            return self._fixed_root(global_steps, 1)
        if stype == FIXED_ROOT:
            return self._fixed_root(global_steps)
        raise RuntimeError("Unsupported curriculum schedule type")

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
