"""Pipelined execution over the 'pipe' mesh axis.

Capability analog of the reference's PipelineEngine
(ref: deepspeed/runtime/pipe/engine.py:46 — instruction interpreter
_exec_schedule :1364, p2p sends :951/:1046, tied-grad reduction :240).
TPU-native design: instead of interpreting an instruction stream with
torch.distributed send/recv, the WHOLE pipeline (all microbatches, all
stages) is ONE jitted shard_map program:

- stage weights = layer-stacked params sharded over the 'pipe' axis;
- activation transfer = `lax.ppermute` to the next stage (rides ICI
  neighbor links, same wire pattern as the reference's p2p :48);
- the microbatch loop is a `lax.scan` over M + P - 1 "clock ticks";
- the backward pipeline comes from autodiff: ppermute's transpose is the
  reverse ppermute, so grad of the scan IS the reverse-order pipeline
  (cooldown bubble included);
- tied weights (e.g. embedding reused by the LM head) are passed
  replicated-over-pipe; shard_map's transpose psums their grads across
  stages — the reference's ReduceTiedGrads dissolves into autodiff.

Other mesh axes (data/fsdp/model/sequence) stay "auto": XLA keeps managing
ZeRO/TP sharding inside each stage.
"""

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import shard_map

PyTree = Any


def stage_index(axis: str = "pipe"):
    return jax.lax.axis_index(axis)


def pipeline_apply(stage_fn: Callable,
                   stage_params: PyTree,
                   x_micro: jnp.ndarray,
                   num_stages: int,
                   *,
                   axis: str = "pipe") -> jnp.ndarray:
    """Run the pipelined forward inside a shard_map context.

    stage_fn(stage_params, x) -> y applies this stage's layer slice.
    x_micro: [M, mb, ...] microbatched stage-0 input (replicated over pipe).
    Returns [M, mb, ...] outputs, valid on the LAST stage (other stages
    hold garbage — mask before use).

    Tick t: stage s computes microbatch (t - s); M + P - 1 ticks total.
    """
    M = x_micro.shape[0]
    num_ticks = M + num_stages - 1
    s = jax.lax.axis_index(axis)
    is_first = s == 0

    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(state, t):
        # stage 0 consumes microbatch t (clipped; out-of-range ticks are
        # bubble and produce masked garbage), others consume what arrived
        inp = jnp.where(is_first,
                        x_micro[jnp.clip(t, 0, M - 1)],
                        state)
        out = stage_fn(stage_params, inp)
        nxt = jax.lax.ppermute(out, axis, perm)
        return nxt, out

    state0 = jnp.zeros_like(x_micro[0])
    _, outs = jax.lax.scan(tick, state0, jnp.arange(num_ticks))
    # last stage's valid outputs live at ticks [P-1, P-1+M)
    return jax.lax.dynamic_slice_in_dim(outs, num_stages - 1, M, axis=0)


def pipeline_loss(stage_fn: Callable,
                  head_loss_fn: Callable,
                  stage_params: PyTree,
                  other_params: PyTree,
                  x_micro: jnp.ndarray,
                  target_micro: PyTree,
                  num_stages: int,
                  *,
                  axis: str = "pipe") -> jnp.ndarray:
    """Pipelined forward + last-stage loss, inside shard_map.

    head_loss_fn(other_params, y, target) -> scalar mean loss for one
    microbatch (runs on the last stage only; other stages' contribution is
    masked to zero and the scalar is psum'd — the analog of the reference's
    _aggregate_total_loss broadcast, ref pipe/engine.py:548).
    """
    y_micro = pipeline_apply(stage_fn, stage_params, x_micro, num_stages,
                             axis=axis)
    s = jax.lax.axis_index(axis)
    is_last = (s == num_stages - 1).astype(jnp.float32)

    def one(y, t):
        return head_loss_fn(other_params, y, t)

    losses = jax.vmap(one)(y_micro, target_micro)          # [M]
    local = jnp.mean(losses) * is_last
    return jax.lax.psum(local, axis)


# ---------------------------------------------------------------------------
# memory-bounded 1F1B execution
# ---------------------------------------------------------------------------

def _zeros_like_f32(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), tree)


def _ring_perms(P_):
    """(forward, backward) neighbor permutations on the pipe ring."""
    return ([(i, (i + 1) % P_) for i in range(P_)],
            [(i, (i - 1) % P_) for i in range(P_)])


def _head_closure(head_loss_fn, target_micro, M):
    """head_for(m): loss closure of the head for microbatch slot m
    (clipped — invalid slots are masked by the caller)."""
    def head_for(m):
        tgt = jax.tree_util.tree_map(
            lambda z: z[jnp.clip(m, 0, M - 1)], target_micro)
        return lambda op, y: head_loss_fn(op, y, tgt)
    return head_for

def _one_f_one_b_program(stage_fn: Callable,
                         head_loss_fn: Callable,
                         num_stages: int,
                         axis: str,
                         stage_params: PyTree,
                         other_params: PyTree,
                         x_micro: jnp.ndarray,
                         target_micro: PyTree):
    """1F1B pipelined forward+backward as ONE scan, inside shard_map.

    Memory-bounded analog of the reference's TrainSchedule
    (ref: deepspeed/runtime/pipe/schedule.py:189): each tick every stage
    runs one forward (microbatch f = t - s) and one backward
    (microbatch b = t - (2P - 2 - s)), so a stage holds at most
    2*(P-1-s) in-flight microbatch *inputs* — O(stages), not
    O(microbatches). Backward recomputes the stage forward from the saved
    input (activation checkpointing at stage granularity, like the
    reference's PipelineModule activation_checkpoint_interval).

    Returns (mean loss, dstage_params, dother_params, dx_micro) — gradients
    computed manually (the caller wraps this in a custom_vjp; autodiff never
    sees the scan, so no O(ticks) residuals are retained).
    """
    M = x_micro.shape[0]
    P_ = num_stages
    s = jax.lax.axis_index(axis)
    is_first = s == 0
    is_last = s == P_ - 1
    num_ticks = M + 2 * P_ - 2
    K = max(2 * P_ - 1, 1)              # input ring-buffer slots

    fwd_perm, bwd_perm = _ring_perms(P_)
    f32 = jnp.float32
    zeros_like_tree = _zeros_like_f32
    head_for = _head_closure(head_loss_fn, target_micro, M)

    def tick(carry, t):
        (fwd_in, bwd_in, buf, dstage, dother, dx_acc, loss_acc) = carry
        f = t - s                        # forward microbatch id
        b = t - (2 * P_ - 2 - s)         # backward microbatch id
        f_valid = (f >= 0) & (f < M)
        b_valid = (b >= 0) & (b < M)

        # ---- forward ----
        inp = jnp.where(is_first, x_micro[jnp.clip(f, 0, M - 1)], fwd_in)
        buf = jnp.where(f_valid,
                        jax.lax.dynamic_update_index_in_dim(
                            buf, inp, jnp.clip(f, 0, M - 1) % K, 0),
                        buf)
        out = stage_fn(stage_params, inp)

        # ---- last-stage head: loss + dy for the just-finished microbatch
        loss_m, head_vjp = jax.vjp(head_for(f), other_params, out)
        dother_m, dy_head = head_vjp(jnp.ones((), loss_m.dtype))
        mask_last = (is_last & f_valid).astype(f32)
        loss_acc = loss_acc + loss_m.astype(f32) * mask_last
        dother = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(f32) * mask_last, dother, dother_m)

        # ---- backward (recompute from the saved stage input) ----
        # at the last stage b == f, and the input is the one stored this tick
        x_saved = jnp.where(is_last, inp, buf[jnp.clip(b, 0, M - 1) % K])
        cot_in = jnp.where(is_last, dy_head.astype(bwd_in.dtype), bwd_in)
        _, stage_vjp = jax.vjp(stage_fn, stage_params, x_saved)
        dstage_m, dx_m = stage_vjp(cot_in)
        mask_b = b_valid.astype(f32)
        dstage = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(f32) * mask_b, dstage, dstage_m)
        # grads w.r.t. the pipeline input (stage 0's dx -> embedding)
        mask_first_b = (is_first & b_valid).astype(dx_m.dtype)
        dx_acc = jax.lax.dynamic_update_index_in_dim(
            dx_acc,
            dx_acc[jnp.clip(b, 0, M - 1)] + dx_m * mask_first_b,
            jnp.clip(b, 0, M - 1), 0)

        # ---- neighbor exchange ----
        fwd_out = jax.lax.ppermute(out, axis, fwd_perm)
        bwd_out = jax.lax.ppermute(dx_m, axis, bwd_perm)
        return (fwd_out, bwd_out, buf, dstage, dother, dx_acc, loss_acc), None

    x0 = jnp.zeros_like(x_micro[0])
    carry0 = (x0, jnp.zeros_like(x0),
              jnp.zeros((K,) + x0.shape, x0.dtype),
              zeros_like_tree(stage_params),
              zeros_like_tree(other_params),
              jnp.zeros_like(x_micro),
              jnp.zeros((), f32))
    (_, _, _, dstage, dother, dx_micro, loss_sum), _ = jax.lax.scan(
        tick, carry0, jnp.arange(num_ticks))

    # per-microbatch mean -> batch mean; scale grads accordingly
    inv_m = 1.0 / M
    loss = jax.lax.psum(loss_sum * inv_m, axis)
    dother = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g * inv_m, axis), dother)
    dx_micro = jax.lax.psum(dx_micro * inv_m, axis)
    dstage = jax.tree_util.tree_map(lambda g: g * inv_m, dstage)
    return loss, dstage, dother, dx_micro


def _make_stashed_grad_loss(run):
    """custom_vjp wrapper shared by the 1F1B and interleaved makers:
    the forward runs the manual fwd+bwd program and stashes the grads
    as residuals; bwd scales them by the incoming cotangent."""

    @jax.custom_vjp
    def loss_fn(stage_params, other_params, x_micro, target_micro):
        loss, _, _, _ = run(stage_params, other_params, x_micro,
                            target_micro)
        return loss

    def fwd(stage_params, other_params, x_micro, target_micro):
        loss, dstage, dother, dx = run(stage_params, other_params,
                                       x_micro, target_micro)
        return loss, (dstage, dother, dx, target_micro)

    def bwd(res, g):
        dstage, dother, dx, target_micro = res
        scale = lambda t: jax.tree_util.tree_map(lambda v_: v_ * g, t)
        dtarget = jax.tree_util.tree_map(
            lambda z: (jnp.zeros(z.shape, jax.dtypes.float0)
                       if not jnp.issubdtype(z.dtype, jnp.floating)
                       else jnp.zeros_like(z)),
            target_micro)
        return scale(dstage), scale(dother), dx * g, dtarget

    loss_fn.defvjp(fwd, bwd)
    return loss_fn


def make_1f1b_loss_fn(stage_fn: Callable,
                      head_loss_fn: Callable,
                      num_stages: int,
                      mesh: Mesh,
                      stage_params_specs: PyTree,
                      *,
                      axis: str = "pipe") -> Callable:
    """(stage_params, other_params, x_micro, target_micro) -> scalar loss,
    differentiable, executing the memory-bounded 1F1B schedule. Gradients
    are produced by the same single scan (custom_vjp; the forward pass
    runs fwd+bwd eagerly and stashes the grads as residuals — train-only,
    eval paths should use the plain pipeline)."""

    def run(stage_params, other_params, x_micro, target_micro):
        prog = partial(_one_f_one_b_program, stage_fn, head_loss_fn,
                       num_stages, axis)
        return shard_map(
            prog, mesh=mesh,
            in_specs=(stage_params_specs, P(), P(), P()),
            out_specs=(P(), stage_params_specs, P(), P()),
            axis_names={axis}, check_vma=False)(
                stage_params, other_params, x_micro, target_micro)

    return _make_stashed_grad_loss(run)


def make_pipelined_loss_fn(embed_fn: Callable,
                           stage_fn: Callable,
                           head_loss_fn: Callable,
                           split_params: Callable,
                           num_stages: int,
                           num_micro: int,
                           mesh: Mesh,
                           stage_params_specs: PyTree,
                           *,
                           remat_stage: bool = True,
                           schedule: str = "1f1b",
                           virtual_chunks: int = 1,
                           axis: str = "pipe") -> Callable:
    """Build an engine-compatible loss fn (params, batch, rng) -> loss.

    - embed_fn(other_params, batch) -> (x [B, ...], targets pytree [B, ...])
      runs replicated on every stage (cheap: embedding lookup).
    - split_params(params) -> (stacked_stage_params, other_params); the
      stacked leaves have leading dim L == layers and are sharded P('pipe')
      on that dim by the caller's partition rules.
    - stage_params_specs: PartitionSpec pytree for the stacked params
      (leading 'pipe' axis); other axes stay auto.
    - schedule: '1f1b' (DEFAULT — memory-bounded, ref TrainSchedule
      pipe/schedule.py:189; activation memory O(stages), which is what
      matters at depth) or 'gpipe' (fill-drain via scan+autodiff;
      activation memory O(microbatches)).

    Under '1f1b' the returned loss_fn carries an ``eval_fn`` attribute
    running the GPipe forward — the 1F1B custom_vjp computes gradients
    eagerly inside its forward, which eval must not pay for; the engine
    picks ``eval_fn`` up automatically.

    schedule='interleaved' runs chunk-granular 1F1B over
    ``virtual_chunks`` virtual stages per device (megatron-style
    interleaving — beyond the reference's schedule set), cutting the
    pipeline bubble by up to ~virtual_chunks at small M/P. The caller
    must feed stage params in virtual-stage stacking order
    (interleave_layer_perm); num_micro must be a multiple of the stage
    count.
    """
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if schedule == "interleaved" and virtual_chunks < 2:
        raise ValueError("schedule='interleaved' needs virtual_chunks >= 2"
                         " (with 1 chunk it IS plain 1f1b)")
    gpipe_stage_fn = stage_fn
    if remat_stage:
        # 1f1b checkpoints at stage granularity by construction; the
        # gpipe path (training or the eval companion) gets explicit remat
        gpipe_stage_fn = jax.checkpoint(
            stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

    if schedule == "1f1b":
        loss_1f1b = make_1f1b_loss_fn(stage_fn, head_loss_fn, num_stages,
                                      mesh, stage_params_specs, axis=axis)
    elif schedule == "interleaved":
        loss_1f1b = make_interleaved_loss_fn(
            stage_fn, head_loss_fn, num_stages, virtual_chunks,
            num_micro, mesh, stage_params_specs, axis=axis)

    def _micro_split(params, batch):
        stage_params, other_params = split_params(params)
        x, targets = embed_fn(other_params, batch)
        B = x.shape[0]
        assert B % num_micro == 0, (B, num_micro)
        mb = B // num_micro
        x_micro = x.reshape((num_micro, mb) + x.shape[1:])
        target_micro = jax.tree_util.tree_map(
            lambda t: t.reshape((num_micro, mb) + t.shape[1:]), targets)
        return stage_params, other_params, x_micro, target_micro

    def _gpipe(params, batch):
        stage_params, other_params, x_micro, target_micro = \
            _micro_split(params, batch)
        inner = partial(pipeline_loss, gpipe_stage_fn, head_loss_fn,
                        num_stages=num_stages, axis=axis)
        sharded = shard_map(
            inner,
            mesh=mesh,
            in_specs=(stage_params_specs,
                      P(),      # other params: replicated over pipe (auto elsewhere)
                      P(),      # x_micro
                      P()),     # targets
            out_specs=P(),
            axis_names={axis},
            check_vma=False)
        return sharded(stage_params, other_params, x_micro, target_micro)

    def loss_fn(params, batch, rng):
        del rng
        if schedule in ("1f1b", "interleaved"):
            stage_params, other_params, x_micro, target_micro = \
                _micro_split(params, batch)
            if schedule == "interleaved":
                # virtual-stage stacking order, applied INSIDE the traced
                # loss: a differentiable gather, so grads scatter back to
                # the natural layout and optimizer state/checkpoints/the
                # gpipe eval companion never see the permuted order
                leaves = jax.tree_util.tree_leaves(stage_params)
                L = leaves[0].shape[0]
                if L % (num_stages * virtual_chunks):
                    # a non-dividing L would silently TRUNCATE the model
                    # (the gather below keeps only the permuted rows)
                    raise ValueError(
                        f"interleaved schedule needs stacked layers "
                        f"({L}) divisible by stages*chunks "
                        f"({num_stages}*{virtual_chunks})")
                perm = jnp.asarray(interleave_layer_perm(
                    L, num_stages, virtual_chunks))
                stage_params = jax.tree_util.tree_map(
                    lambda p: p[perm], stage_params)
            return loss_1f1b(stage_params, other_params, x_micro,
                             target_micro)
        return _gpipe(params, batch)

    if schedule in ("1f1b", "interleaved"):
        def eval_fn(params, batch, rng):
            del rng
            return _gpipe(params, batch)
        loss_fn.eval_fn = eval_fn

    return loss_fn


# ---------------------------------------------------------------------------
# interleaved 1F1B (virtual pipeline stages)
# ---------------------------------------------------------------------------

def interleave_layer_perm(L: int, P_: int, v: int):
    """Row permutation putting a [L]-stacked layer pytree into virtual-
    stage order: device d's contiguous 'pipe' slab then holds its v
    chunks (virtual stages c*P+d) back to back. Applied INSIDE the
    traced loss (a differentiable gather — autodiff scatters grads back
    to the natural order), so optimizer state and checkpoints keep the
    natural layer layout."""
    import numpy as np
    Lv = L // (v * P_)
    rows = []
    for d in range(P_):
        for c in range(v):
            base = (c * P_ + d) * Lv
            rows.extend(range(base, base + Lv))
    return np.asarray(rows)


def _buffer_depths(tab, P_: int, v: int, M: int):
    """Max in-flight (received-not-yet-consumed) microbatches per (device,
    chunk), for the activation and cotangent ring buffers. Consumption
    order per chunk is increasing microbatch id, so slot = m %% K is
    collision-free for K = max window."""
    T = tab["fwd_c"].shape[1]
    V = v * P_
    k_act = 1
    k_cot = 1
    for d in range(P_):
        for c in range(v):
            vs = c * P_ + d
            # activation for F(c, m) arrives at the producer's F tick
            # (prev virtual stage) or is read straight from x_micro
            # (vs == 0); consumed by B(c, m)
            if vs > 0:
                pd, pc = (d - 1, c) if d > 0 else (P_ - 1, c - 1)
                recv = {tab["fwd_m"][pd, t]: t for t in range(T)
                        if tab["fwd_valid"][pd, t]
                        and tab["fwd_c"][pd, t] == pc}
            else:
                recv = {tab["fwd_m"][d, t]: t for t in range(T)
                        if tab["fwd_valid"][d, t]
                        and tab["fwd_c"][d, t] == c}
            cons = {tab["bwd_m"][d, t]: t for t in range(T)
                    if tab["bwd_valid"][d, t] and tab["bwd_c"][d, t] == c}
            for t in range(T):
                live = [m for m in recv
                        if recv[m] <= t and cons.get(m, T + 1) > t]
                if live:
                    k_act = max(k_act, max(live) - min(live) + 1)
            # cotangent for B(c, m): produced by the next virtual
            # stage's B (or the local head F when vs == V-1)
            if vs == V - 1:
                crecv = {tab["fwd_m"][d, t]: t for t in range(T)
                         if tab["fwd_valid"][d, t]
                         and tab["fwd_c"][d, t] == c}
            else:
                nd, nc = (d + 1, c) if d < P_ - 1 else (0, c + 1)
                crecv = {tab["bwd_m"][nd, t]: t for t in range(T)
                         if tab["bwd_valid"][nd, t]
                         and tab["bwd_c"][nd, t] == nc}
            for t in range(T):
                live = [m for m in crecv
                        if crecv[m] <= t and cons.get(m, T + 1) > t]
                if live:
                    k_cot = max(k_cot, max(live) - min(live) + 1)
    return k_act, k_cot


def _interleaved_program(stage_fn, head_loss_fn, num_stages, v, tables,
                         k_act, k_cot, axis,
                         stage_params, other_params, x_micro,
                         target_micro):
    """Interleaved 1F1B as ONE scan over the precomputed lockstep tick
    tables (runtime/pipe/schedule.py interleaved_1f1b_tables): each tick
    every device runs at most one chunk-forward and one chunk-backward,
    at (chunk, microbatch) coordinates read from the table — the
    schedule is data, not control flow. Activations/cotangents hop
    devices via ppermute; each device's stacked slab is [v, Lv, ...]
    with the chunk picked by dynamic index. Cuts the pipeline bubble by
    up to ~v at small M/P (see schedule.py; megatron-style virtual
    stages — beyond the reference's schedule set, ref deepspeed/runtime/
    pipe/schedule.py:182)."""
    M = x_micro.shape[0]
    P_ = num_stages
    V = v * P_
    d = jax.lax.axis_index(axis)
    T = tables["fwd_c"].shape[1]
    tab = {k: jnp.asarray(val) for k, val in tables.items()}

    fwd_perm, bwd_perm = _ring_perms(P_)
    f32 = jnp.float32
    zeros_like_tree = _zeros_like_f32
    head_for = _head_closure(head_loss_fn, target_micro, M)

    # local slab [v*Lv, ...] -> [v, Lv, ...]
    slab = jax.tree_util.tree_map(
        lambda p: p.reshape((v, p.shape[0] // v) + p.shape[1:]),
        stage_params)

    def chunk_params(c):
        return jax.tree_util.tree_map(lambda p: p[c], slab)

    x0 = jnp.zeros_like(x_micro[0])

    def tick(carry, t):
        (act_buf, cot_buf, dstage, dother, dx_acc, loss_acc) = carry

        # ---- forward: one chunk-F at the table's coordinates ----
        fc = tab["fwd_c"][d, t]
        fm = tab["fwd_m"][d, t]
        fv = tab["fwd_valid"][d, t] == 1
        vs_f = fc * P_ + d
        inp = jnp.where(vs_f == 0, x_micro[jnp.clip(fm, 0, M - 1)],
                        act_buf[fc, jnp.clip(fm, 0, M - 1) % k_act])
        out = stage_fn(chunk_params(fc), inp)

        # last virtual stage: head loss + cotangent, delivered locally
        loss_m, head_vjp = jax.vjp(head_for(fm), other_params, out)
        dother_m, dy_head = head_vjp(jnp.ones((), loss_m.dtype))
        m_head = ((vs_f == V - 1) & fv).astype(f32)
        loss_acc = loss_acc + loss_m.astype(f32) * m_head
        dother = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(f32) * m_head, dother, dother_m)
        cot_buf = jnp.where(
            m_head > 0,
            cot_buf.at[fc, jnp.clip(fm, 0, M - 1) % k_cot].set(
                dy_head.astype(cot_buf.dtype)),
            cot_buf)

        # ship the activation to device d+1; store what arrives from d-1
        recv_act = jax.lax.ppermute(out, axis, fwd_perm)
        pd = (d - 1) % P_
        sfc = tab["fwd_c"][pd, t]
        sfm = tab["fwd_m"][pd, t]
        svs = sfc * P_ + pd
        rc = jnp.where(d == 0, sfc + 1, sfc)      # my chunk for that msg
        r_ok = ((tab["fwd_valid"][pd, t] == 1) & (svs < V - 1)
                & (rc < v))
        act_buf = jnp.where(
            r_ok,
            act_buf.at[jnp.clip(rc, 0, v - 1),
                       jnp.clip(sfm, 0, M - 1) % k_act].set(recv_act),
            act_buf)

        # ---- backward: one chunk-B at the table's coordinates ----
        bc = tab["bwd_c"][d, t]
        bm = tab["bwd_m"][d, t]
        bv = tab["bwd_valid"][d, t] == 1
        vs_b = bc * P_ + d
        x_saved = jnp.where(vs_b == 0, x_micro[jnp.clip(bm, 0, M - 1)],
                            act_buf[bc, jnp.clip(bm, 0, M - 1) % k_act])
        cot_in = cot_buf[bc, jnp.clip(bm, 0, M - 1) % k_cot]
        _, svjp = jax.vjp(stage_fn, chunk_params(bc), x_saved)
        dchunk, dx_m = svjp(cot_in.astype(x_saved.dtype))
        m_b = bv.astype(f32)
        dstage = jax.tree_util.tree_map(
            lambda acc, g: acc.at[bc].add(g.astype(f32) * m_b),
            dstage, dchunk)
        # embedding grads (virtual stage 0) accumulate per microbatch
        m_b0 = ((vs_b == 0) & bv).astype(dx_m.dtype)
        dx_acc = dx_acc.at[jnp.clip(bm, 0, M - 1)].add(dx_m * m_b0)

        # ship the cotangent to device d-1; store what arrives from d+1
        recv_cot = jax.lax.ppermute(dx_m, axis, bwd_perm)
        nd = (d + 1) % P_
        nbc = tab["bwd_c"][nd, t]
        nbm = tab["bwd_m"][nd, t]
        nvs = nbc * P_ + nd
        rcb = jnp.where(d == P_ - 1, nbc - 1, nbc)
        rb_ok = ((tab["bwd_valid"][nd, t] == 1) & (nvs > 0) & (rcb >= 0))
        cot_buf = jnp.where(
            rb_ok,
            cot_buf.at[jnp.clip(rcb, 0, v - 1),
                       jnp.clip(nbm, 0, M - 1) % k_cot].set(
                recv_cot.astype(cot_buf.dtype)),
            cot_buf)

        return (act_buf, cot_buf, dstage, dother, dx_acc, loss_acc), None

    carry0 = (jnp.zeros((v, k_act) + x0.shape, x0.dtype),
              jnp.zeros((v, k_cot) + x0.shape, f32),
              zeros_like_tree(slab),
              zeros_like_tree(other_params),
              jnp.zeros_like(x_micro),
              jnp.zeros((), f32))
    (_, _, dstage, dother, dx_micro, loss_sum), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))

    inv_m = 1.0 / M
    loss = jax.lax.psum(loss_sum * inv_m, axis)
    dother = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g * inv_m, axis), dother)
    dx_micro = jax.lax.psum(dx_micro * inv_m, axis)
    # [v, Lv, ...] grads -> the [v*Lv, ...] slab layout of the input
    dstage = jax.tree_util.tree_map(
        lambda g: g.reshape((g.shape[0] * g.shape[1],) + g.shape[2:]) *
        inv_m, dstage)
    return loss, dstage, dother, dx_micro


def make_interleaved_loss_fn(stage_fn, head_loss_fn, num_stages, v,
                             num_micro, mesh, stage_params_specs, *,
                             axis: str = "pipe"):
    """(stage_params_virtual, other_params, x_micro, target_micro) ->
    scalar loss under the interleaved 1F1B schedule; differentiable via
    the same stashed-grads custom_vjp shape as make_1f1b_loss_fn.
    stage_params_virtual must be stacked in VIRTUAL-STAGE order
    (interleave_layer_perm) so the 'pipe' sharding gives each device its
    v chunks."""
    from deepspeed_tpu.runtime.pipe.schedule import interleaved_1f1b_tables
    tables = interleaved_1f1b_tables(num_stages, v, num_micro)
    k_act, k_cot = _buffer_depths(tables, num_stages, v, num_micro)

    def run(stage_params, other_params, x_micro, target_micro):
        prog = partial(_interleaved_program, stage_fn, head_loss_fn,
                       num_stages, v, tables, k_act, k_cot, axis)
        return shard_map(
            prog, mesh=mesh,
            in_specs=(stage_params_specs, P(), P(), P()),
            out_specs=(P(), stage_params_specs, P(), P()),
            axis_names={axis}, check_vma=False)(
                stage_params, other_params, x_micro, target_micro)

    return _make_stashed_grad_loss(run)
