"""Pipelined execution over the 'pipe' mesh axis.

Capability analog of the reference's PipelineEngine
(ref: deepspeed/runtime/pipe/engine.py:46 — instruction interpreter
_exec_schedule :1364, p2p sends :951/:1046, tied-grad reduction :240).
TPU-native design: instead of interpreting an instruction stream with
torch.distributed send/recv, the WHOLE pipeline (all microbatches, all
stages) is ONE jitted shard_map program:

- stage weights = layer-stacked params sharded over the 'pipe' axis;
- activation transfer = `lax.ppermute` to the next stage (rides ICI
  neighbor links, same wire pattern as the reference's p2p :48);
- the microbatch loop is a `lax.scan` over M + P - 1 "clock ticks";
- the backward pipeline comes from autodiff: ppermute's transpose is the
  reverse ppermute, so grad of the scan IS the reverse-order pipeline
  (cooldown bubble included);
- tied weights (e.g. embedding reused by the LM head) are passed
  replicated-over-pipe; shard_map's transpose psums their grads across
  stages — the reference's ReduceTiedGrads dissolves into autodiff.

Other mesh axes (data/fsdp/model/sequence) stay "auto": XLA keeps managing
ZeRO/TP sharding inside each stage.
"""

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


def stage_index(axis: str = "pipe"):
    return jax.lax.axis_index(axis)


def pipeline_apply(stage_fn: Callable,
                   stage_params: PyTree,
                   x_micro: jnp.ndarray,
                   num_stages: int,
                   *,
                   axis: str = "pipe") -> jnp.ndarray:
    """Run the pipelined forward inside a shard_map context.

    stage_fn(stage_params, x) -> y applies this stage's layer slice.
    x_micro: [M, mb, ...] microbatched stage-0 input (replicated over pipe).
    Returns [M, mb, ...] outputs, valid on the LAST stage (other stages
    hold garbage — mask before use).

    Tick t: stage s computes microbatch (t - s); M + P - 1 ticks total.
    """
    M = x_micro.shape[0]
    num_ticks = M + num_stages - 1
    s = jax.lax.axis_index(axis)
    is_first = s == 0

    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(state, t):
        # stage 0 consumes microbatch t (clipped; out-of-range ticks are
        # bubble and produce masked garbage), others consume what arrived
        inp = jnp.where(is_first,
                        x_micro[jnp.clip(t, 0, M - 1)],
                        state)
        out = stage_fn(stage_params, inp)
        nxt = jax.lax.ppermute(out, axis, perm)
        return nxt, out

    state0 = jnp.zeros_like(x_micro[0])
    _, outs = jax.lax.scan(tick, state0, jnp.arange(num_ticks))
    # last stage's valid outputs live at ticks [P-1, P-1+M)
    return jax.lax.dynamic_slice_in_dim(outs, num_stages - 1, M, axis=0)


def pipeline_loss(stage_fn: Callable,
                  head_loss_fn: Callable,
                  stage_params: PyTree,
                  other_params: PyTree,
                  x_micro: jnp.ndarray,
                  target_micro: PyTree,
                  num_stages: int,
                  *,
                  axis: str = "pipe") -> jnp.ndarray:
    """Pipelined forward + last-stage loss, inside shard_map.

    head_loss_fn(other_params, y, target) -> scalar mean loss for one
    microbatch (runs on the last stage only; other stages' contribution is
    masked to zero and the scalar is psum'd — the analog of the reference's
    _aggregate_total_loss broadcast, ref pipe/engine.py:548).
    """
    y_micro = pipeline_apply(stage_fn, stage_params, x_micro, num_stages,
                             axis=axis)
    s = jax.lax.axis_index(axis)
    is_last = (s == num_stages - 1).astype(jnp.float32)

    def one(y, t):
        return head_loss_fn(other_params, y, t)

    losses = jax.vmap(one)(y_micro, target_micro)          # [M]
    local = jnp.mean(losses) * is_last
    return jax.lax.psum(local, axis)


def make_pipelined_loss_fn(embed_fn: Callable,
                           stage_fn: Callable,
                           head_loss_fn: Callable,
                           split_params: Callable,
                           num_stages: int,
                           num_micro: int,
                           mesh: Mesh,
                           stage_params_specs: PyTree,
                           *,
                           remat_stage: bool = True,
                           axis: str = "pipe") -> Callable:
    """Build an engine-compatible loss fn (params, batch, rng) -> loss.

    - embed_fn(other_params, batch) -> (x [B, ...], targets pytree [B, ...])
      runs replicated on every stage (cheap: embedding lookup).
    - split_params(params) -> (stacked_stage_params, other_params); the
      stacked leaves have leading dim L == layers and are sharded P('pipe')
      on that dim by the caller's partition rules.
    - stage_params_specs: PartitionSpec pytree for the stacked params
      (leading 'pipe' axis); other axes stay auto.
    """
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn,
                                  policy=jax.checkpoint_policies.nothing_saveable)

    def loss_fn(params, batch, rng):
        del rng
        stage_params, other_params = split_params(params)
        x, targets = embed_fn(other_params, batch)
        B = x.shape[0]
        assert B % num_micro == 0, (B, num_micro)
        mb = B // num_micro
        x_micro = x.reshape((num_micro, mb) + x.shape[1:])
        target_micro = jax.tree_util.tree_map(
            lambda t: t.reshape((num_micro, mb) + t.shape[1:]), targets)

        inner = partial(pipeline_loss, stage_fn, head_loss_fn,
                        num_stages=num_stages, axis=axis)

        sharded = jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(stage_params_specs,
                      P(),      # other params: replicated over pipe (auto elsewhere)
                      P(),      # x_micro
                      P()),     # targets
            out_specs=P(),
            axis_names={axis},
            check_vma=False)
        return sharded(stage_params, other_params, x_micro, target_micro)

    return loss_fn
