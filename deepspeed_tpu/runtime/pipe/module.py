"""Pipeline model description: layer lists, stage partitioning, tied layers.

Capability analog of the reference's PipelineModule
(ref: deepspeed/runtime/pipe/module.py:87; LayerSpec :25, TiedLayerSpec :73,
partitioning _partition_layers :363 with uniform/parameters/type:regex
methods). TPU-native difference: a "stage" is not a process — it's a slice
of the 'pipe' mesh axis, and layer params live in pytrees; so this module
does the *math* (which layer goes to which stage, tied-weight groups) and
hands specs to the shard_map pipeline engine.
"""

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.utils.logging import logger


@dataclass
class LayerSpec:
    """Delayed layer construction (ref: module.py:25). ``build(rng)`` returns
    (params, apply_fn) where apply_fn(params, x, rng) -> y."""
    typename: str
    build: Callable  # (rng) -> (params, apply_fn)
    count_params: Optional[Callable] = None  # () -> int

    def param_count(self) -> int:
        return self.count_params() if self.count_params else 0


@dataclass
class TiedLayerSpec(LayerSpec):
    """Layer sharing weights with all layers of the same ``key``
    (ref: module.py:73). The pipeline engine replicates tied params across
    the stages that use them and psums their grads over the tie group
    (ref: PipelineEngine._exec_reduce_tied_grads engine.py:240)."""
    key: str = ""


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Balanced contiguous split; returns part boundaries len=num_parts+1
    (ref: deepspeed/runtime/utils.py partition_uniform)."""
    assert num_parts > 0
    parts = [0] * (num_parts + 1)
    chunk, rem = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < rem else 0)
    assert parts[-1] == num_items
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split minimizing the max part weight via binary search over the
    bottleneck (ref: deepspeed/runtime/utils.py partition_balanced)."""
    n = len(weights)
    assert num_parts > 0
    if num_parts >= n:
        return partition_uniform(n, num_parts)
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def parts_for(bottleneck: float) -> Optional[List[int]]:
        parts = [0]
        for _ in range(num_parts):
            start = parts[-1]
            # furthest end with weight <= bottleneck
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= bottleneck:
                end += 1
            if end == start:  # single item exceeds bottleneck
                return None
            parts.append(end)
        return parts if parts[-1] == n else None

    lo = max(weights) if weights else 0.0
    hi = prefix[-1]
    for _ in range(50):
        mid = (lo + hi) / 2
        if parts_for(mid) is None:
            lo = mid
        else:
            hi = mid
    parts = parts_for(hi)
    assert parts is not None
    return parts


class PipelineModule:
    """Partitions a layer list over pipeline stages.

    partition_method (ref: module.py:87 docstring):
      'uniform'       equal layer counts
      'parameters'    balance on per-layer parameter counts
      'type:REGEX'    balance on layers whose typename matches REGEX
    """

    def __init__(self, layers: List[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None):
        self.layers = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.parts = self._partition_layers()
        self.tied_groups = self._build_tied_groups()

    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.layers)
        if method == "uniform":
            return partition_uniform(n, self.num_stages)
        if method == "parameters":
            weights = [max(1.0, float(l.param_count())) for l in self.layers]
            return partition_balanced(weights, self.num_stages)
        if method.startswith("type:"):
            pat = re.compile(method[5:], re.IGNORECASE)
            weights = [1.0 if pat.search(l.typename) else 0.0
                       for l in self.layers]
            if sum(weights) == 0:
                raise ValueError(f"no layers match {method}")
            return partition_balanced(weights, self.num_stages)
        raise NotImplementedError(f"partition method {method}")

    def _build_tied_groups(self) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for idx, spec in enumerate(self.layers):
            if isinstance(spec, TiedLayerSpec):
                groups.setdefault(spec.key, []).append(idx)
        return groups

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def layers_of_stage(self, stage_id: int) -> List[int]:
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    def tied_stages(self, key: str) -> List[int]:
        return sorted({self.stage_of_layer(i) for i in self.tied_groups[key]})

    def describe(self) -> str:
        lines = []
        for s in range(self.num_stages):
            names = [self.layers[i].typename for i in self.layers_of_stage(s)]
            lines.append(f"stage {s}: {names}")
        return "\n".join(lines)
