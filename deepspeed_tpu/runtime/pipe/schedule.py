"""Pipeline instruction schedules — pure Python, no devices.

Capability analog of the reference's schedule module
(ref: deepspeed/runtime/pipe/schedule.py — PipeSchedule :24, TrainSchedule
:182, InferenceSchedule :129, DataParallelSchedule :292; instruction set
:317-463). On TPU the hot path executes as ONE fused shard_map program
(deepspeed_tpu/runtime/pipe/engine.py) rather than an interpreted
instruction stream, but the schedule objects remain: they document and test
the 1F1B ordering, drive the (host-side) offload scheduler, and give users
the same introspection surface (see ref tests/unit/test_pipe_schedule.py,
which validates instruction streams without any GPU — mirrored in
tests/test_pipe_schedule.py).
"""

from typing import Iterator, List


# ---------------------------------------------------------------------------
# instructions
# ---------------------------------------------------------------------------

class PipeInstruction:
    """One step of work (ref: schedule.py:317)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        if not self.kwargs:
            return self.name
        args = ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items()))
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (self.__class__ is other.__class__ and
                self.kwargs == other.kwargs)

    def __hash__(self):
        return hash((self.__class__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Step the optimizer (all stages, after all microbatches)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction."""


class ReduceTiedGrads(PipeInstruction):
    """Reduce gradients of tied modules across their tie group."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipe buffer slot
    (ref: schedule.py:355 — carries buffer_id)."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------

class PipeSchedule:
    """Yields lists of PipeInstructions per "clock step" for one stage
    (ref: schedule.py:24)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (ref: schedule.py:129)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            valid = 0 <= micro_batch_id < self.micro_batches
            buf = self._buffer_idx(max(micro_batch_id, 0))
            if valid:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady-state interleave, cooldown backwards
    (ref: schedule.py:182 TrainSchedule.steps).

    Per-stage sequence for stage s of P with M microbatches:
      warmup   = min(P - 1 - s, M) forwards
      steady   = interleaved 1F1B
      cooldown = remaining backwards
    Peak live activations on stage s = warmup + 1 (the 1F1B memory win
    over GPipe's M).
    """

    def num_pipe_buffers(self) -> int:
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def steps(self):
        M, P, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(P - 1 - s, M)
        fwd_id = 0
        bwd_id = 0
        cmds_seq: List[List[PipeInstruction]] = []

        # warmup forwards
        for _ in range(warmup):
            cmds_seq.append(self._fwd_cmds(fwd_id))
            fwd_id += 1
        # steady state: 1F1B
        while fwd_id < M:
            cmds_seq.append(self._fwd_cmds(fwd_id))
            fwd_id += 1
            cmds_seq.append(self._bwd_cmds(bwd_id))
            bwd_id += 1
        # cooldown backwards
        while bwd_id < M:
            cmds_seq.append(self._bwd_cmds(bwd_id))
            bwd_id += 1
        # epilogue: grad reduction + optimizer step
        cmds_seq.append([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
        yield from cmds_seq

    def _fwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buf))
        else:
            cmds.append(RecvActivation(buf))
        cmds.append(ForwardPass(buf))
        if not self.is_last_stage:
            cmds.append(SendActivation(buf))
        return cmds

    def _bwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buf))
        cmds.append(BackwardPass(buf))
        if not self.is_first_stage:
            cmds.append(SendGrad(buf))
        return cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate schedule for pure DP (ref: schedule.py:292)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for micro_batch_id in range(self.micro_batches):
            cmds: List[PipeInstruction] = [
                LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if micro_batch_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds
