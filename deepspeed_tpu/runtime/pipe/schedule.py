"""Pipeline instruction schedules — pure Python, no devices.

Capability analog of the reference's schedule module
(ref: deepspeed/runtime/pipe/schedule.py — PipeSchedule :24, TrainSchedule
:182, InferenceSchedule :129, DataParallelSchedule :292; instruction set
:317-463). On TPU the hot path executes as ONE fused shard_map program
(deepspeed_tpu/runtime/pipe/engine.py) rather than an interpreted
instruction stream, but the schedule objects remain: they document and test
the 1F1B ordering, drive the (host-side) offload scheduler, and give users
the same introspection surface (see ref tests/unit/test_pipe_schedule.py,
which validates instruction streams without any GPU — mirrored in
tests/test_pipe_schedule.py).
"""

from typing import Iterator, List


# ---------------------------------------------------------------------------
# instructions
# ---------------------------------------------------------------------------

class PipeInstruction:
    """One step of work (ref: schedule.py:317)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        if not self.kwargs:
            return self.name
        args = ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items()))
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (self.__class__ is other.__class__ and
                self.kwargs == other.kwargs)

    def __hash__(self):
        return hash((self.__class__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Step the optimizer (all stages, after all microbatches)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction."""


class ReduceTiedGrads(PipeInstruction):
    """Reduce gradients of tied modules across their tie group."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipe buffer slot
    (ref: schedule.py:355 — carries buffer_id)."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------

class PipeSchedule:
    """Yields lists of PipeInstructions per "clock step" for one stage
    (ref: schedule.py:24)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (ref: schedule.py:129)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            valid = 0 <= micro_batch_id < self.micro_batches
            buf = self._buffer_idx(max(micro_batch_id, 0))
            if valid:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady-state interleave, cooldown backwards
    (ref: schedule.py:182 TrainSchedule.steps).

    Per-stage sequence for stage s of P with M microbatches:
      warmup   = min(P - 1 - s, M) forwards
      steady   = interleaved 1F1B
      cooldown = remaining backwards
    Peak live activations on stage s = warmup + 1 (the 1F1B memory win
    over GPipe's M).
    """

    def num_pipe_buffers(self) -> int:
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def steps(self):
        M, P, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(P - 1 - s, M)
        fwd_id = 0
        bwd_id = 0
        cmds_seq: List[List[PipeInstruction]] = []

        # warmup forwards
        for _ in range(warmup):
            cmds_seq.append(self._fwd_cmds(fwd_id))
            fwd_id += 1
        # steady state: 1F1B
        while fwd_id < M:
            cmds_seq.append(self._fwd_cmds(fwd_id))
            fwd_id += 1
            cmds_seq.append(self._bwd_cmds(bwd_id))
            bwd_id += 1
        # cooldown backwards
        while bwd_id < M:
            cmds_seq.append(self._bwd_cmds(bwd_id))
            bwd_id += 1
        # epilogue: grad reduction + optimizer step
        cmds_seq.append([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
        yield from cmds_seq

    def _fwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buf))
        else:
            cmds.append(RecvActivation(buf))
        cmds.append(ForwardPass(buf))
        if not self.is_last_stage:
            cmds.append(SendActivation(buf))
        return cmds

    def _bwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buf))
        cmds.append(BackwardPass(buf))
        if not self.is_first_stage:
            cmds.append(SendGrad(buf))
        return cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate schedule for pure DP (ref: schedule.py:292)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for micro_batch_id in range(self.micro_batches):
            cmds: List[PipeInstruction] = [
                LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if micro_batch_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds


# ---------------------------------------------------------------------------
# interleaved 1F1B (virtual pipeline stages)
# ---------------------------------------------------------------------------

def _interleaved_rank_order(P: int, v: int, M: int, d: int):
    """Device d's op sequence for the interleaved 1F1B schedule
    (Megatron-style: warmup forwards, then chunk-granular 1F1B pairs,
    then cooldown backwards). Each op is ('F'|'B', chunk, microbatch).

    Virtual stage c*P + d holds the d-th slice of layer-chunk c; the
    k-th forward on any device cycles chunks every P microbatch slots:
    chunk = (k %% (P*v)) // P, mb = (k // (P*v))*P + k %% P. Backwards
    walk the same slots with chunks reversed. M must be a multiple of P
    (the cycling assumes full groups).
    """
    if M % P:
        raise ValueError(
            f"interleaved schedule needs num_micro divisible by the "
            f"stage count (chunk cycling assumes full groups), got "
            f"{M} microbatches over {P} stages")
    total = M * v

    def fwd_slot(k):
        return ((k % (P * v)) // P,
                (k // (P * v)) * P + (k % P))

    def bwd_slot(k):
        c, m = fwd_slot(k)
        return v - 1 - c, m

    warmup = min((P - d - 1) * 2 + (v - 1) * P, total)
    ops = [("F",) + fwd_slot(k) for k in range(warmup)]
    for j in range(total - warmup):
        ops.append(("F",) + fwd_slot(warmup + j))
        ops.append(("B",) + bwd_slot(j))
    for j in range(total - warmup, total):
        ops.append(("B",) + bwd_slot(j))
    return ops


def interleaved_1f1b_tables(P: int, v: int, M: int):
    """Lockstep tick tables for interleaved 1F1B over P devices with v
    layer chunks per device (virtual stages V = v*P, chunk c of device d
    is virtual stage c*P + d).

    The per-device op order (_interleaved_rank_order) is scheduled
    greedily into synchronous ticks: a tick holds at most one F and one
    B per device, in the device's own order, and an op waits until its
    producer ran at an EARLIER tick (cross-device messages arrive the
    tick after they are sent; the last virtual stage's F->B handoff is
    local and may share a tick). This compiles the reference's
    interpreted instruction stream (ref: deepspeed/runtime/pipe/
    schedule.py:182, megatron interleaving) into static arrays an SPMD
    lax.scan can index — no host control flow at run time.

    Returns a dict of int32 numpy arrays of shape [P, T]:
      fwd_c/fwd_m/fwd_valid — chunk, microbatch, validity of the tick's F
      bwd_c/bwd_m/bwd_valid — same for the tick's B
    """
    import numpy as np

    V = v * P
    orders = [_interleaved_rank_order(P, v, M, d) for d in range(P)]
    ptr = [0] * P
    done_f = {}                      # (c, m, d) -> tick
    done_b = {}
    rows = []                        # per tick: [P] of (fop|None, bop|None)

    def vstage(c, d):
        return c * P + d

    def f_ready(c, m, d, t):
        vs = vstage(c, d)
        if vs == 0:
            return True
        pc, pd = (c, d - 1) if d > 0 else (c - 1, P - 1)
        return done_f.get((pc, m, pd), t) < t

    def b_ready(c, m, d, t):
        vs = vstage(c, d)
        if vs == V - 1:              # local head handoff: same tick ok
            return done_f.get((c, m, d), t + 1) <= t
        nc, nd = (c, d + 1) if d < P - 1 else (c + 1, 0)
        return (done_b.get((nc, m, nd), t) < t
                and done_f.get((c, m, d), t + 1) <= t)

    t = 0
    while any(ptr[d] < len(orders[d]) for d in range(P)):
        row = [[None, None] for _ in range(P)]
        for d in range(P):
            used_f = used_b = False
            # up to one F and one B, in this device's own order
            for _ in range(2):
                if ptr[d] >= len(orders[d]):
                    break
                kind, c, m = orders[d][ptr[d]]
                if kind == "F" and not used_f and f_ready(c, m, d, t):
                    done_f[(c, m, d)] = t
                    row[d][0] = (c, m)
                    used_f = True
                    ptr[d] += 1
                elif kind == "B" and not used_b and b_ready(c, m, d, t):
                    done_b[(c, m, d)] = t
                    row[d][1] = (c, m)
                    used_b = True
                    ptr[d] += 1
                else:
                    break            # in-order: blocked op stalls the rest
        rows.append(row)
        t += 1
        if t > 4 * (M * v + 2 * V):
            raise RuntimeError(
                "interleaved schedule deadlock — dependency rules and "
                "rank op order disagree (scheduler bug)")

    T = len(rows)
    out = {k: np.zeros((P, T), np.int32)
           for k in ("fwd_c", "fwd_m", "fwd_valid",
                     "bwd_c", "bwd_m", "bwd_valid")}
    for tt, row in enumerate(rows):
        for d in range(P):
            if row[d][0] is not None:
                c, m = row[d][0]
                out["fwd_c"][d, tt] = c
                out["fwd_m"][d, tt] = m
                out["fwd_valid"][d, tt] = 1
            if row[d][1] is not None:
                c, m = row[d][1]
                out["bwd_c"][d, tt] = c
                out["bwd_m"][d, tt] = m
                out["bwd_valid"][d, tt] = 1
    return out
