"""Activation checkpointing (rematerialization) API.

Capability match for the reference's activation checkpointing module
(ref: deepspeed/runtime/activation_checkpointing/checkpointing.py —
``configure`` :708, ``checkpoint`` :693, ``CheckpointFunction`` :405,
``is_configured`` :738). The reference re-implements torch's checkpoint
with partitioned/contiguous/CPU-offloaded activation storage and manual
RNG bookkeeping; under XLA all of that collapses into ``jax.checkpoint``
with a *policy*:

* default                      → save nothing, recompute all
  (``nothing_saveable`` — max memory saving, the reference default)
* ``partition_activations``    → saved residuals keep their sharded
  layout automatically under pjit (XLA never gathers a value just to
  save it), so this is a no-op we accept for API parity
* ``cpu_checkpointing``        → offload saved residuals to pinned host
  memory (``save_and_offload_only_these_names`` over values tagged with
  :func:`checkpoint_name`)
* ``number_checkpoints``       → informational (the scan-over-layers
  models remat per layer, the same N-segment behavior)
* RNG state                    → jax PRNG keys are values, not global
  state; replay is exact by construction (the reference's
  CudaRNGStatesTracker :189 dissolves)

``checkpoint(fn, *args)`` and the ``CheckpointFunction`` alias mirror
the reference call sites, so porting a model is mechanical.
"""

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist

# re-export: models tag offloadable activations with
# jax.ad_checkpoint.checkpoint_name(x, "name")
from jax.ad_checkpoint import checkpoint_name  # noqa: F401

_config = None


class _ActCkptState:
    def __init__(self, partition_activations=False, number_checkpoints=None,
                 contiguous_checkpointing=False, checkpoint_in_cpu=False,
                 synchronize=False, profile=False,
                 offload_names=("act",)):
        self.partition_activations = partition_activations
        self.number_checkpoints = number_checkpoints
        self.contiguous_checkpointing = contiguous_checkpointing
        self.checkpoint_in_cpu = checkpoint_in_cpu
        self.synchronize = synchronize
        self.profile = profile
        self.offload_names = tuple(offload_names)

    def policy(self):
        if self.checkpoint_in_cpu:
            return jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=list(self.offload_names),
                offload_src="device", offload_dst="pinned_host")
        return jax.checkpoint_policies.nothing_saveable


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None,
              num_checkpoints: Optional[int] = None,
              offload_names=("act",)) -> None:
    """(ref: checkpointing.py:708) explicit args override the
    ``activation_checkpointing`` section of ``deepspeed_config`` (a
    DeepSpeedConfig or dict)."""
    global _config
    del mpu_  # mesh axes replace the mpu (API parity)
    base = _ActCkptState(offload_names=offload_names)
    if deepspeed_config is not None:
        ac = deepspeed_config
        if hasattr(ac, "activation_checkpointing"):
            ac = ac.activation_checkpointing
        elif isinstance(ac, dict):
            from deepspeed_tpu.runtime.config import (
                ActivationCheckpointingConfig)
            ac = ActivationCheckpointingConfig.from_dict(
                ac.get("activation_checkpointing"))
        base.partition_activations = ac.partition_activations
        base.number_checkpoints = ac.number_checkpoints
        base.contiguous_checkpointing = ac.contiguous_memory_optimization
        base.checkpoint_in_cpu = ac.cpu_checkpointing
        base.synchronize = ac.synchronize_checkpoint_boundary
        base.profile = ac.profile
    for name, val in (("partition_activations", partition_activations),
                      ("contiguous_checkpointing", contiguous_checkpointing),
                      ("checkpoint_in_cpu", checkpoint_in_cpu),
                      ("synchronize", synchronize),
                      ("profile", profile),
                      ("number_checkpoints", num_checkpoints)):
        if val is not None:
            setattr(base, name, val)
    _config = base
    log_dist(
        f"activation checkpointing configured: cpu_offload="
        f"{base.checkpoint_in_cpu}, partition={base.partition_activations}",
        ranks=[0])


def is_configured() -> bool:
    """(ref: checkpointing.py:738)"""
    return _config is not None


def reset() -> None:
    """(ref: checkpointing.py:745 reset of buffers) clears the global
    config; jax frees remat buffers automatically."""
    global _config
    _config = None


def current_policy():
    return (_config or _ActCkptState()).policy()


def checkpoint(function: Callable, *args) -> Any:
    """Recompute-in-backward apply (ref: checkpointing.py:693
    ``checkpoint(function, *args)``)."""
    return jax.checkpoint(function, policy=current_policy())(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form for scan bodies / blocks. The policy is read at
    CALL time, so configure() after wrapping still applies."""
    def wrapped(*args, **kwargs):
        return jax.checkpoint(
            function, policy=current_policy())(*args, **kwargs)
    return wrapped


# reference-name alias: torch autograd.Function dissolves into the
# functional transform
CheckpointFunction = checkpoint


def model_parallel_cuda_manual_seed(seed: int):  # pragma: no cover
    """API parity shim (ref: checkpointing.py:282): jax PRNG keys are
    explicit values; fold the TP axis index into the key instead."""
    raise RuntimeError(
        "jax PRNG keys are explicit — use "
        "jax.random.fold_in(key, axis_index) inside shard_map rather "
        "than global per-device RNG state.")
