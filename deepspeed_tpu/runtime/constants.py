"""Config keys and defaults.

TPU-native analog of the reference's centralized key/default registry
(ref: deepspeed/runtime/constants.py, deepspeed/runtime/zero/constants.py).
Every JSON config key recognized by ``DeepSpeedConfig`` lives here.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER,
    LAMB_OPTIMIZER, FUSED_LAMB_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
]

#############################################
# Precision (fp16 / bf16 / fp32)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0  # 0 => dynamic
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1.0
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

PRECISION_DEFAULT = "fp32"

#############################################
# Gradient clipping / misc training knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

SEED = "seed"
SEED_DEFAULT = 1234

#############################################
# ZeRO (sharding) — ref deepspeed/runtime/zero/constants.py
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = 0
ZERO_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_REDUCE_SCATTER = "reduce_scatter"
ZERO_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OVERLAP_COMM = "overlap_comm"
ZERO_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OFFLOAD_PARAM = "offload_param"
ZERO_OFFLOAD_OPTIMIZER = "offload_optimizer"
ZERO_STAGE3_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_STAGE3_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_STAGE3_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_STAGE3_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_STAGE3_GATHER_16BIT_WEIGHTS_ON_MODEL_SAVE = "stage3_gather_16bit_weights_on_model_save"
ZERO_ROUND_ROBIN_GRADIENTS = "round_robin_gradients"
ZERO_ELASTIC_CHECKPOINT = "elastic_checkpoint"

OFFLOAD_DEVICE = "device"
OFFLOAD_DEVICE_NONE = "none"
OFFLOAD_DEVICE_CPU = "cpu"
OFFLOAD_DEVICE_NVME = "nvme"
OFFLOAD_NVME_PATH = "nvme_path"
OFFLOAD_BUFFER_COUNT = "buffer_count"
OFFLOAD_BUFFER_SIZE = "buffer_size"
OFFLOAD_PIN_MEMORY = "pin_memory"
OFFLOAD_PIPELINE_READ = "pipeline_read"
OFFLOAD_PIPELINE_WRITE = "pipeline_write"
OFFLOAD_MAX_IN_CPU = "max_in_cpu"

#############################################
# Parallel topology (TPU-native: one mesh with named axes)
#############################################
MESH = "mesh"
MESH_DATA = "data"               # pure data parallel axis
MESH_FSDP = "fsdp"               # ZeRO-3 parameter-sharding axis
MESH_MODEL = "model"             # tensor parallel axis
MESH_PIPE = "pipe"               # pipeline stage axis
MESH_EXPERT = "expert"           # expert parallel axis
MESH_SEQUENCE = "sequence"       # sequence/context parallel axis

TENSOR_PARALLEL_SIZE = "tensor_parallel_size"
TENSOR_PARALLEL_SIZE_DEFAULT = 1
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
PIPELINE_PARALLEL_SIZE_DEFAULT = 1
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
SEQUENCE_PARALLEL_SIZE_DEFAULT = 1
EXPERT_PARALLEL_SIZE = "expert_parallel_size"
EXPERT_PARALLEL_SIZE_DEFAULT = 1
# ZeRO-3 only: split the dp degree into replica_parallel_size outer
# 'data' replicas (the DCN-crossing axis) x fsdp shards inside each
# replica — the layout dcn_compressed composes with (PERF.md
# "Compressed DCN x ZeRO-fsdp")
REPLICA_PARALLEL_SIZE = "replica_parallel_size"
REPLICA_PARALLEL_SIZE_DEFAULT = 1

#############################################
# Pipeline engine
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "parameters"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"

#############################################
# Activation checkpointing (ref runtime/activation_checkpointing/config)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CKPT_PROFILE = "profile"

#############################################
# Sparse / flash / ring attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"

#############################################
# Curriculum learning (ref runtime/data_pipeline)
#############################################
CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_ENABLED = "enabled"
CURRICULUM_ENABLED_DEFAULT = False

#############################################
# Progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Tensorboard / monitoring
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedTPUJobName"

#############################################
# Flops profiler
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 1
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True
FLOPS_PROFILER_OUTPUT_FILE = "output_file"
FLOPS_PROFILER_OUTPUT_FILE_DEFAULT = None

#############################################
# Autotuning
#############################################
AUTOTUNING = "autotuning"
AUTOTUNING_ENABLED = "enabled"
AUTOTUNING_ENABLED_DEFAULT = False

#############################################
# Elasticity (ref elasticity/constants.py)
#############################################
ELASTICITY = "elasticity"
ELASTICITY_ENABLED = "enabled"
ELASTICITY_ENABLED_DEFAULT = False
MAX_ACCELERATORS = "max_train_batch_size"
MICRO_BATCHES = "micro_batch_sizes"
MIN_ACCELERATORS = "min_gpus"
MAX_ACCELERATORS_KEY = "max_gpus"
MIN_TIME = "min_time"
VERSION = "version"
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
PREFER_LARGER_BATCH = "prefer_larger_batch"

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "checkpoint_tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"

#############################################
# Quantization / MoQ (ref runtime/quantize.py config keys)
#############################################
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False

#############################################
# Communication compression (1-bit family)
#############################################
COMPRESSED_COMM = "compressed_communication"
COMM_BACKEND_NAME = "comm_backend_name"
COMM_BACKEND_NAME_DEFAULT = "ici"  # "ici" (XLA collectives) or "dcn_compressed"

#############################################
# Data types
#############################################
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

# config-driven LoRA section (runtime/lora.py)
LORA = "lora"
