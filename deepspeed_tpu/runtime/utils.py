"""Runtime numeric utilities: global norm, clipping, memory reporting.

TPU-native equivalent of deepspeed/runtime/utils.py (clip_grad_norm_,
get_global_norm, see_memory_usage, CheckOverflow). Model-parallel-aware
norm reduction is unnecessary here: when grads are sharded over mesh axes,
``jnp`` reductions under jit produce globally-correct norms because XLA
inserts the cross-device psum automatically.
"""

import gc
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

try:
    import psutil
    PSUTIL = True
except ImportError:  # pragma: no cover
    PSUTIL = False

PyTree = Any


def global_norm(tree: PyTree) -> jnp.ndarray:
    """L2 norm over an entire pytree (ref: runtime/utils.py get_global_norm)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def clip_by_global_norm(tree: PyTree, max_norm: float,
                        norm: Optional[jnp.ndarray] = None) -> PyTree:
    """Scale the whole tree so its global norm is <= max_norm
    (ref: runtime/utils.py clip_grad_norm_)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda l: (l * scale).astype(l.dtype), tree)


def count_parameters(tree: PyTree) -> int:
    return sum(int(jnp.size(l)) for l in jax.tree_util.tree_leaves(tree))


def tree_bytes(tree: PyTree) -> int:
    return sum(l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "dtype"))


def see_memory_usage(message: str, force: bool = False):
    """Host + device memory snapshot (ref: runtime/utils.py see_memory_usage)."""
    if not force:
        return
    gc.collect()
    parts = [message]
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                used = stats.get("bytes_in_use", 0) / 2**30
                limit = stats.get("bytes_limit", 0) / 2**30
                parts.append(f"{d}: {used:.2f}/{limit:.2f} GB")
    except Exception:  # dslint: disable=DS006 — debug-string probe; backends without memory_stats just omit it
        pass
    if PSUTIL:
        vm = psutil.virtual_memory()
        parts.append(f"host used={vm.used / 2**30:.2f}GB ({vm.percent}%)")
    logger.info(" | ".join(parts))


def call_to_str(base: str, *args, **kwargs) -> str:
    """Pretty-print a call (ref: runtime/utils.py call_to_str)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{key}={repr(arg)}" for key, arg in kwargs.items())
    name += ")"
    return name
