"""DeepSpeedEngine — the training engine.

TPU-native analog of the reference engine (ref: deepspeed/runtime/engine.py:168
DeepSpeedEngine; forward :1523, backward :1636, step :1840). The torch
engine mutates module/optimizer state across three calls; under XLA the
whole micro-step pipeline (forward, backward, gradient accumulation,
reduction, overflow check, clip, optimizer update, lr schedule) is ONE
compiled SPMD program: ``train_batch()``. ``forward/backward/step`` wrappers
are provided for API familiarity but delegate to the fused step.

ZeRO stages are realized purely through shardings (see
deepspeed_tpu/parallel/sharding.py): XLA emits the reduce-scatter /
allgather traffic the reference drives by hand with backward hooks
(stage_1_and_2.py:773) and the stage-3 parameter coordinator
(partitioned_param_coordinator.py:45).
"""

import os
import time
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.adam import adagrad, fused_adam
from deepspeed_tpu.ops.lamb import fused_lamb
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel import sharding as sharding_lib
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime import loss_scaler as ls
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
from deepspeed_tpu.runtime.utils import (clip_by_global_norm, count_parameters,
                                         global_norm)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.jax_compat import shard_map
from deepspeed_tpu.utils.timer import (NoopTimer, SynchronizedWallClockTimer,
                                       ThroughputTimer, TRAIN_BATCH_TIMER)

PyTree = Any
LossFn = Callable[..., Any]  # (params, batch, rng) -> loss  or (loss, aux)


class TrainState:
    """Functional train state threaded through the jitted step.

    Registered as a pytree; holds the fp32 master params (ref: the flat
    fp32 groups of FP16_Optimizer / BF16_Optimizer,
    runtime/fp16/fused_optimizer.py:18, runtime/bf16_optimizer.py:75),
    optimizer state, loss-scale state and step counter.
    """

    def __init__(self, step, params, opt_state, scale_state, rng,
                 comm_error=None):
        self.step = step
        self.params = params
        self.opt_state = opt_state
        self.scale_state = scale_state
        self.rng = rng
        # per-DP-rank error-feedback residual for compressed gradient
        # reduction (comm_backend_name="dcn_compressed"; ref: the worker
        # error tensors of NcclBackend.compressed_allreduce, nccl.py:52)
        self.comm_error = comm_error

    def tree_flatten(self):
        return ((self.step, self.params, self.opt_state, self.scale_state,
                 self.rng, self.comm_error), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: s.tree_flatten(),
    TrainState.tree_unflatten)


def _cast_tree(tree: PyTree, dtype) -> PyTree:
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


class DeepSpeedEngine:
    """Training engine over one device mesh.

    Parameters
    ----------
    loss_fn : callable(params, batch, rng) -> loss | (loss, aux-dict)
        The model's loss. Computed in the configured precision; params
        arrive already cast to the compute dtype.
    params : pytree of fp32 arrays (the master weights).
    config : DeepSpeedConfig
    mesh : optional prebuilt Mesh (defaults to mesh_from_config).
    partition_rules : optional TP rules (parallel/sharding.PartitionRule).
    optimizer : optional optax.GradientTransformation overriding the config.
    lr_schedule : optional callable(step)->lr overriding the config.
    """

    def __init__(self,
                 loss_fn: LossFn,
                 params: PyTree,
                 config: DeepSpeedConfig,
                 mesh: Optional[Mesh] = None,
                 partition_rules: Optional[Sequence] = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_schedule: Optional[Callable] = None,
                 has_aux: bool = False,
                 donate_state: bool = True):
        self.config = config
        self.loss_fn = loss_fn
        self.has_aux = has_aux
        self.mesh = mesh if mesh is not None else mesh_lib.mesh_from_config(config)
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.client_lr_schedule = lr_schedule

        self.dp_world_size = mesh_lib.dp_world_size(self.mesh)
        self.mp_world_size = mesh_lib.axis_size(self.mesh, "model")

        # --- elasticity v0.1 enforcement (ref: engine.py:425 + the
        # elastic batch resolution in deepspeed/__init__.py) -----------
        if config.elasticity_enabled:
            from deepspeed_tpu.elasticity import (
                compute_elastic_config, ensure_immutable_elastic_config)
            from deepspeed_tpu.version import __version__ as _ver
            ensure_immutable_elastic_config(config.elasticity_dict)
            # the batch identity is global = micro x gas x DP-replicas,
            # so the validated world is the DP degree; under TP/PP the
            # scheduler's chip count is dp x (mp x pp), and valid_gpus
            # entries denote DP replicas
            final_bs, _valid, _micro = compute_elastic_config(
                {"elasticity": config.elasticity_dict}, _ver,
                world_size=self.dp_world_size)
            if not config.elasticity_dict.get(
                    "ignore_non_elastic_batch_info", False) and \
                    config.train_batch_size != final_bs:
                raise ValueError(
                    f"train_batch_size={config.train_batch_size} conflicts "
                    f"with the elastic batch size {final_bs}; set it to "
                    f"{final_bs} or ignore_non_elastic_batch_info=true")
        from deepspeed_tpu.utils import groups as groups_lib
        groups_lib.set_mesh(self.mesh)

        # --- precision ------------------------------------------------
        self.compute_dtype = config.compute_dtype
        self.fp16_enabled = config.fp16.enabled
        self.bf16_enabled = config.bf16.enabled
        self.dynamic_loss_scale = config.fp16.dynamic_loss_scale
        # memory-efficient bf16: bf16 masters (stochastic-rounding update)
        # + bf16 Adam moments (see BF16Config.memory_efficient)
        self.memory_efficient_bf16 = (config.bf16.enabled
                                      and config.bf16.memory_efficient)
        if config.bf16.memory_efficient and not config.bf16.enabled:
            raise ValueError("bf16.memory_efficient requires bf16.enabled")
        self.master_dtype = (jnp.bfloat16 if self.memory_efficient_bf16
                             else jnp.float32)

        # --- config-driven LoRA (runtime/lora.py) ---------------------
        # adapt BEFORE specs/optimizer so adapter leaves shard and the
        # masked transform sees the final tree
        if config.lora.enabled:
            if config.zero.offload_optimizer.enabled:
                raise ValueError(
                    "lora + offload_optimizer makes no sense: the host "
                    "optimizer exists for multi-GB optimizer state, "
                    "which LoRA removes — drop one of the two")
            from deepspeed_tpu.runtime import lora as lora_lib
            if not isinstance(params.get("block"), dict):
                raise ValueError(
                    "config-driven lora adapts the models/* layout "
                    "(a 'block' dict of dense entries); for a custom "
                    "pytree call runtime.lora.add_lora yourself and "
                    "pass optimizer=lora_optimizer(...)")
            adapted_entries = [e for e in params["block"].values()
                               if isinstance(e, dict) and "lora_a" in e]
            if adapted_entries:
                # resume path: the tree is already adapted — the config
                # knobs must AGREE with it (rank is readable from the
                # adapter shapes; silently training a different rank
                # than the config claims would be worse than an error)
                got_rank = adapted_entries[0]["lora_a"].shape[-1]
                if got_rank != config.lora.rank:
                    raise ValueError(
                        f"params carry rank-{got_rank} adapters but the "
                        f"config says lora.rank={config.lora.rank}")
                got_alpha = float(
                    jnp.ravel(adapted_entries[0]["lora_scale"])[0]
                    * got_rank)
                if abs(got_alpha - config.lora.alpha) > 1e-6:
                    raise ValueError(
                        f"params carry alpha={got_alpha:g} adapters but "
                        f"the config says lora.alpha={config.lora.alpha}")
                got_targets = sorted(
                    n for n, e in params["block"].items()
                    if isinstance(e, dict) and "lora_a" in e)
                want = sorted(n for n in config.lora.targets
                              if n in params["block"])
                if got_targets != want:
                    raise ValueError(
                        f"params adapt {got_targets} but the config's "
                        f"lora.targets resolve to {want}")
            else:
                params = lora_lib.add_lora(
                    params, jax.random.PRNGKey(config.lora.seed),
                    rank=config.lora.rank, alpha=config.lora.alpha,
                    targets=config.lora.targets)
                if not any("lora_a" in e
                           for e in params["block"].values()
                           if isinstance(e, dict)):
                    raise ValueError(
                        f"lora.targets {config.lora.targets} matched no "
                        f"dense entry in the model block "
                        f"({sorted(params['block'])}) — every parameter "
                        f"would be frozen and training would be a no-op")

        # --- shardings ------------------------------------------------
        self.partition_rules = list(partition_rules or [])
        self.param_pspecs = sharding_lib.param_specs(
            params, self.mesh, zero_stage=config.zero.stage,
            rules=self.partition_rules,
            min_shard_size=config.zero.stage3_min_shard_size)
        self.param_shardings = sharding_lib.to_named(self.param_pspecs, self.mesh)

        # --- lr schedule & optimizer ---------------------------------
        self.lr_schedule = self._configure_lr_schedule(lr_schedule)

        # host offload of optimizer state (ZeRO-Offload/Infinity; see
        # runtime/zero/offload.py) — master weights + moments on host,
        # only compute-dtype params on device
        self.offload_enabled = (config.zero.offload_optimizer.enabled
                                and optimizer is None)
        self.dpu_enabled = (self.offload_enabled
                            and config.zero.offload_optimizer
                            .delayed_param_update)
        self._dpu_pending = None
        if self.dpu_enabled:
            if config.fp16.enabled:
                raise ValueError(
                    "delayed_param_update requires bf16 (fp16 overflow "
                    "skipping cannot compose with one-step staleness)")
            import concurrent.futures as _fut
            self._dpu_executor = _fut.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ds-dpu")
        if self.offload_enabled:
            self._configure_offload_optimizer(params)
            self.optimizer = None
            opt_state = None
            # device_params() already assembles onto the mesh shardings
            params = self.host_optimizer.device_params()
        else:
            params = jax.device_put(_cast_tree(params, self.master_dtype),
                                    self.param_shardings)
            self.optimizer = optimizer if optimizer is not None \
                else self._configure_basic_optimizer()
            if config.lora.enabled:
                from deepspeed_tpu.runtime import lora as lora_lib
                self.optimizer = lora_lib.lora_optimizer(
                    self.optimizer, params)

            # optimizer state: shard like ZeRO stage >= 1
            opt_shape = jax.eval_shape(self.optimizer.init, params)
            self.opt_pspecs = sharding_lib.opt_state_specs(
                opt_shape, self.param_pspecs, params, self.mesh,
                zero_stage=config.zero.stage,
                min_shard_size=config.zero.stage3_min_shard_size)
            self.opt_shardings = sharding_lib.to_named(self.opt_pspecs, self.mesh)
            opt_state = jax.jit(self.optimizer.init,
                                out_shardings=self.opt_shardings)(params)

        scale_state = ls.init_state(
            static_scale=config.fp16.loss_scale if self.fp16_enabled else 1.0,
            initial_scale_power=config.fp16.initial_scale_power,
            hysteresis=config.fp16.hysteresis) if self.fp16_enabled \
            else ls.init_state(static_scale=1.0)

        # --- compressed DP gradient reduction (dcn_compressed) --------
        # the engine-level analog of the reference's compressed allreduce
        # backend (ref: runtime/comm/nccl.py:52): grads cross the wire as
        # packed 1-bit signs + scales with per-rank error feedback
        self.compressed_comm = config.comm_backend_name == "dcn_compressed"
        comm_error = None
        if self.compressed_comm:
            self._validate_compressed_comm()
            comm_error = self._init_comm_error(params)

        rng = jax.random.PRNGKey(config.seed)
        self.state = TrainState(
            step=jnp.zeros([], jnp.int32),
            params=params,
            opt_state=opt_state,
            scale_state=scale_state,
            rng=rng,
            comm_error=comm_error)

        # --- metrics monitor (ref: engine.py:470-517 tensorboard) -----
        if config.tensorboard.enabled:
            from deepspeed_tpu.utils.monitor import Monitor
            self.monitor = Monitor.from_config(config.tensorboard)
        else:
            from deepspeed_tpu.utils.monitor import NoopMonitor
            self.monitor = NoopMonitor()
        self._monitor_buffer = []
        if config.tensorboard.enabled:
            # scalars are buffered between steps_per_print boundaries (a
            # per-step float() would sync the device); make sure a process
            # that never calls destroy() still lands its tail
            import atexit
            atexit.register(self._flush_monitor_buffer)

        # --- timers ---------------------------------------------------
        self.wall_clock_breakdown = config.wall_clock_breakdown
        self.timers = SynchronizedWallClockTimer() if self.wall_clock_breakdown \
            else NoopTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print)

        # --- MoQ quantize-aware training (ref: engine.py:1789-1800) ---
        qt = config.quantize_training
        if qt.enabled:
            from deepspeed_tpu.runtime.quantize import Quantizer
            self.quantizer = Quantizer.from_config(qt)
            if qt.eigenvalue.enabled:
                from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
                ecfg = qt.eigenvalue
                self.eigenvalue = Eigenvalue(
                    verbose=ecfg.verbose, max_iter=ecfg.max_iter,
                    tol=ecfg.tol, stability=ecfg.stability,
                    gas_boundary_resolution=ecfg.gas_boundary_resolution,
                    layer_name=ecfg.layer_name, layer_num=ecfg.layer_num)
            else:
                self.eigenvalue = None
        else:
            self.quantizer = None
            self.eigenvalue = None
        self.block_eigenvalue = {}

        def _eigenvalue_loss(p, b, r):
            out = self.loss_fn(p, b, r)
            return out[0] if self.has_aux else out
        # stable identity so Eigenvalue's jitted HVP cache hits
        self._eigenvalue_loss = _eigenvalue_loss

        # --- curriculum learning (ref: engine.py:1548-1554) -----------
        if config.curriculum.enabled:
            from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler
            cc = config.curriculum
            self.curriculum_scheduler = CurriculumScheduler({
                "curriculum_type": cc.curriculum_type,
                "min_difficulty": cc.min_difficulty,
                "max_difficulty": cc.max_difficulty,
                "schedule_type": cc.schedule_type,
                "schedule_config": cc.schedule_config})
        else:
            self.curriculum_scheduler = None

        # --- progressive layer drop (ref: engine.py:1542) -------------
        if config.pld.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                ProgressiveLayerDrop)
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.pld.theta, gamma=config.pld.gamma)
        else:
            self.progressive_layer_drop = None

        # --- compiled programs ---------------------------------------
        self._donate_state = donate_state
        if self.offload_enabled:
            self._train_step = None
            self._grad_step = self._build_grad_step()
        else:
            self._train_step = self._build_train_step(donate_state)
        self._eval_step = self._build_eval_step()

        n_params = count_parameters(params)
        log_dist(
            f"engine ready: {n_params / 1e6:.2f}M params, zero_stage="
            f"{config.zero.stage}, precision={config.precision_name}, "
            f"dp={self.dp_world_size}, tp={self.mp_world_size}, "
            f"micro_bs={config.train_micro_batch_size_per_gpu}, "
            f"gas={config.gradient_accumulation_steps}", ranks=[0])
        self._warn_hbm_headroom(n_params)

    def _warn_hbm_headroom(self, n_params: int) -> None:
        """Best-effort warning when the per-device TRAINING STATE alone
        (params + optimizer moments [+ masters] + a gradient buffer) sits
        within the compile-headroom of device HBM — borderline-HBM
        programs put this backend's compiler into a multi-minute fitting
        grind (see utils/hbm.py and PERF.md). State is the part the
        engine can compute without knowing the model architecture;
        activations come on top, so a warning here means near-certain
        trouble. Never raises: the user may know better."""
        if (self.offload_enabled or self.config.zero.offload_param.enabled):
            return  # moments/params live on host — state model doesn't apply
        from deepspeed_tpu.utils import hbm as hbm_guard
        try:
            cap = hbm_guard.device_hbm_bytes(self.mesh.devices.flat[0]
                                             if self.mesh is not None
                                             else None)
        except Exception:
            cap = None
        if cap is None:
            return
        sb = hbm_guard.state_bytes(
            n_params, self.config.precision_name,
            self.config.bf16.memory_efficient,
            (self.config.optimizer.type or "").lower())
        # TP shards every tensor over 'model'; ZeRO shards optimizer
        # (stage>=1), grads (>=2) and params (>=3) over data/fsdp
        tp = max(1, self.mp_world_size)
        shards = max(1, self.dp_world_size)
        pb = 4 if self.config.precision_name == "fp32" else 2
        state = sb["params"] // tp
        if self.config.zero.stage >= 3:
            state //= shards
        state += sb["optimizer"] // tp // (shards if self.config.zero.stage
                                           >= 1 else 1)
        state += n_params * pb // tp // (shards if self.config.zero.stage
                                         >= 2 else 1)  # gradient buffer
        limit = cap - int(hbm_guard.DEFAULT_HEADROOM_GIB * hbm_guard.GiB)
        if state > limit:
            logger.warning(
                f"training state alone is ~{state / hbm_guard.GiB:.1f}GiB "
                f"per device vs {cap / hbm_guard.GiB:.0f}GiB HBM "
                f"(compile-safe limit {limit / hbm_guard.GiB:.1f}GiB, "
                f"before activations) — expect OOM or a pathological "
                f"borderline-HBM compile. Consider zero stage 3 over more "
                f"devices, bf16.memory_efficient, or offload.")

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _configure_lr_schedule(self, override):
        if override is not None:
            return override
        base_lr = (self.config.optimizer.params or {}).get("lr", 1e-3)
        sched_cfg = self.config.scheduler
        return get_lr_schedule(sched_cfg.type, sched_cfg.params, base_lr=base_lr)

    def _configure_basic_optimizer(self) -> optax.GradientTransformation:
        """Config-name -> optimizer (ref: engine.py:1108
        _configure_basic_optimizer)."""
        ocfg = self.config.optimizer
        name = (ocfg.type or C.ADAMW_OPTIMIZER).lower()
        p = dict(ocfg.params or {})
        lr = self.lr_schedule
        betas = p.get("betas", (0.9, 0.999))
        eps = p.get("eps", 1e-8)
        wd = p.get("weight_decay", 0.0)

        if name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER,
                    C.CPU_ADAM_OPTIMIZER):
            adam_w_mode = p.get("adam_w_mode", name != C.ADAM_OPTIMIZER or wd == 0.0)
            if name == C.ADAMW_OPTIMIZER:
                adam_w_mode = True
            return fused_adam(lr, b1=betas[0], b2=betas[1], eps=eps,
                              weight_decay=wd, adam_w_mode=adam_w_mode,
                              state_dtype=(jnp.bfloat16 if
                                           self.memory_efficient_bf16
                                           else None))
        if self.memory_efficient_bf16:
            raise ValueError(
                "bf16.memory_efficient supports the Adam family only "
                f"(got optimizer {name!r})")
        if name in (C.LAMB_OPTIMIZER, C.FUSED_LAMB_OPTIMIZER):
            return fused_lamb(lr, b1=betas[0], b2=betas[1],
                              eps=p.get("eps", 1e-6), weight_decay=wd,
                              max_coeff=p.get("max_coeff", 10.0),
                              min_coeff=p.get("min_coeff", 0.01))
        if name == C.SGD_OPTIMIZER:
            return optax.chain(
                optax.trace(decay=p.get("momentum", 0.0), nesterov=p.get("nesterov", False)),
                optax.scale_by_schedule(lambda c: -lr(c)) if callable(lr) else optax.scale(-lr))
        if name == C.ADAGRAD_OPTIMIZER:
            return adagrad(lr, eps=eps, weight_decay=wd)
        if name in (C.ONEBIT_ADAM_OPTIMIZER, C.ONEBIT_LAMB_OPTIMIZER,
                    C.ZERO_ONE_ADAM_OPTIMIZER):
            from deepspeed_tpu.runtime.comm.onebit import (onebit_adam,
                                                           onebit_lamb,
                                                           zero_one_adam)
            factory = {C.ONEBIT_ADAM_OPTIMIZER: onebit_adam,
                       C.ONEBIT_LAMB_OPTIMIZER: onebit_lamb,
                       C.ZERO_ONE_ADAM_OPTIMIZER: zero_one_adam}[name]
            return factory(lr, config_params=p)
        raise ValueError(f"unknown optimizer {name}")

    def _configure_offload_optimizer(self, params: PyTree):
        """Build the host-resident optimizer for ZeRO-Offload/Infinity
        (ref: stage_1_and_2.py:1725 CPU Adam step path; NVMe via
        swap_tensor swappers). Master fp32 weights + moments live on host;
        see runtime/zero/offload.py for the architecture."""
        from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
        ocfg = self.config.optimizer
        name = (ocfg.type or C.ADAMW_OPTIMIZER).lower()
        if name not in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER,
                        C.FUSED_ADAM_OPTIMIZER, C.CPU_ADAM_OPTIMIZER,
                        C.ADAGRAD_OPTIMIZER):
            raise ValueError(
                "offload_optimizer supports the Adam family and Adagrad, "
                f"got {name}")
        p = dict(ocfg.params or {})
        off = self.config.zero.offload_optimizer
        nvme = off.nvme_path if off.device == C.OFFLOAD_DEVICE_NVME else None
        if off.device == C.OFFLOAD_DEVICE_NVME and nvme is None:
            raise ValueError("offload_optimizer.device=nvme needs nvme_path")
        self.host_optimizer = HostOffloadOptimizer(
            params, self.lr_schedule,
            betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=p.get("adam_w_mode", True) or name == C.ADAMW_OPTIMIZER,
            nvme_path=nvme,
            pipeline_swap=off.pipeline_read or off.pipeline_write,
            param_dtype=self.compute_dtype,
            shardings=self.param_shardings,
            optimizer=("adagrad" if name == C.ADAGRAD_OPTIMIZER
                       else "adam"))

    # ------------------------------------------------------------------
    # compressed DP gradient reduction (comm_backend_name="dcn_compressed")
    # ------------------------------------------------------------------
    def _validate_compressed_comm(self) -> None:
        """Compressed reduction covers plain data parallelism with ZeRO
        stage <= 2 — one stage BEYOND the reference's 1-bit backends
        (stage <= 1, ref: onebit docs + stage checks in
        runtime/fp16/onebit/adam.py): stage 2's gradient partitioning
        dissolves here (the sharded optimizer update consumes its slice
        of the compressed-averaged gradient in the auto domain, outside
        the manual-'data' shard_map), so per-rank gradients stay whole
        exactly as error feedback requires. Stage 3 composes via the
        PERF.md scheme ('Compressed DCN x ZeRO-fsdp'): the 'fsdp' axis
        stays AUTO inside the manual-'data' shard_map, so XLA keeps the
        exact per-layer param gathers and the exact gradient
        reduce-scatter over fsdp/ICI, while the manual wire carries
        1-bit payloads of each device's 1/fsdp grad shard across
        'data'/DCN — compression and sharding multiply (per-rank DCN
        bytes P/(8*fsdp); ref scope: the reference's 1-bit backends
        stop at stage 1, runtime/fp16/onebit/adam.py:14)."""
        for axis in ("model", "pipe", "sequence"):
            if mesh_lib.axis_size(self.mesh, axis) > 1:
                raise ValueError(
                    f"dcn_compressed composes with data/fsdp parallelism "
                    f"only; mesh axis '{axis}' has size > 1")
        if (self.config.zero.stage == 3
                and mesh_lib.axis_size(self.mesh, "data") == 1):
            raise ValueError(
                "dcn_compressed with zero stage 3 requires "
                "mesh.replica_parallel_size > 1: with a single replica "
                "there is no cross-replica ('data') axis to compress — "
                "1-bit noise over the exact fsdp arithmetic is pure loss "
                "(PERF.md 'Compressed DCN x ZeRO-fsdp')")
        if self.offload_enabled:
            raise ValueError("dcn_compressed and offload_optimizer are "
                             "mutually exclusive")

    def _init_comm_error(self, params: PyTree) -> PyTree:
        """Per-replica error-feedback residuals: leaf shape
        [n_data, *param]; leading dim sharded over 'data' so each
        replica holds one param-shaped fp32 residual (ref: the
        worker_error buffers of nccl.py compressed_allreduce). Under
        ZeRO-3 the param dims additionally keep the leaf's fsdp
        sharding — each DEVICE then holds exactly the residual for its
        own 1/fsdp grad shard, and nothing is replicated."""
        ndata = mesh_lib.axis_size(self.mesh, "data")

        def err_sharding(psp):
            return NamedSharding(self.mesh, P("data", *tuple(psp)))

        def make(p, psp):
            return jax.device_put(
                jnp.zeros((ndata,) + tuple(p.shape), jnp.float32),
                err_sharding(psp))

        return jax.tree_util.tree_map(make, params, self.param_pspecs)

    def _comm_error_shardings(self) -> PyTree:
        return jax.tree_util.tree_map(
            lambda psp: NamedSharding(self.mesh, P("data", *tuple(psp))),
            self.param_pspecs)

    # ------------------------------------------------------------------
    # compiled step construction
    # ------------------------------------------------------------------
    def _build_train_step(self, donate_state: bool):
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        clip = cfg.gradient_clipping
        fp16 = self.fp16_enabled
        compute_dtype = self.compute_dtype
        loss_fn = self.loss_fn
        has_aux = self.has_aux
        optimizer = self.optimizer
        prescale = cfg.prescale_gradients
        predivide = cfg.gradient_predivide_factor

        # MoQ: fake-quantize the compute-dtype copy inside the step; the
        # fp32 masters stay full precision (ref: engine.py:1789-1800
        # quantizes optimizer.bit16_groups, not the fp32 masters)
        quant_fn = self.quantizer.make_transform(
            step_at_build=self.global_steps - self.skipped_steps) \
            if (self.quantizer is not None and self.quantizer.active) else None
        pld_cfg = cfg.pld if cfg.pld.enabled else None

        def micro_loss(params, micro_batch, rng, scale_state, step):
            cparams = _cast_tree(params, compute_dtype)
            if quant_fn is not None:
                rng, qr = jax.random.split(rng)
                cparams = quant_fn(cparams, qr, step)
            # cast float inputs too (ref: engine.py:951 half()/bfloat16() cast
            # of module AND inputs) so activations genuinely run on the MXU in
            # the reduced precision
            micro_batch = _cast_tree(micro_batch, compute_dtype)
            if pld_cfg is not None and isinstance(micro_batch, dict):
                # PLD keep-prob: a pure function of the step counter,
                # threaded as a traced scalar (ref: engine.py:1542 injects
                # it as a fwd kwarg host-side)
                from deepspeed_tpu.runtime.progressive_layer_drop import (
                    PLD_THETA_KEY, theta_schedule)
                micro_batch = dict(micro_batch)
                micro_batch[PLD_THETA_KEY] = theta_schedule(
                    step, pld_cfg.theta, pld_cfg.gamma)
            out = loss_fn(cparams, micro_batch, rng)
            if has_aux:
                loss, aux = out
            else:
                loss, aux = out, {}
            scaled = ls.scale_loss(loss.astype(jnp.float32), scale_state) if fp16 else loss
            return scaled.astype(jnp.float32), (loss, aux)

        grad_fn = jax.grad(micro_loss, has_aux=True)

        compressed = self.compressed_comm
        mesh = self.mesh

        def accum_grads(params, batch, step_rng, scale_state, step):
            """Gradient accumulation over microbatches (lax.scan).
            Under jit the batch's data sharding makes XLA emit the DP
            reduction; inside shard_map (compressed path) it yields the
            rank-local gradients."""
            def micro_body(carry, micro):
                grads_acc, loss_acc, r = carry
                r, mr = jax.random.split(r)
                g, (loss, _aux) = grad_fn(params, micro, mr,
                                          scale_state, step)
                if prescale and predivide != 1.0:
                    g = jax.tree_util.tree_map(lambda x: x / predivide, g)
                grads_acc = jax.tree_util.tree_map(
                    lambda a, b: (a + b.astype(a.dtype)), grads_acc, g)
                return (grads_acc, loss_acc + loss.astype(jnp.float32), r), None

            # memory-efficient mode keeps the accumulator in bf16 (half
            # the transient grad memory — what lets 1.5B-class training
            # state + grads fit one 16GB chip); gas is typically 1 there,
            # so fp32 accumulation buys nothing
            acc_dtype = (jnp.bfloat16 if self.memory_efficient_bf16
                         else jnp.float32)
            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, acc_dtype), params)
            if gas > 1:
                micro_batches = jax.tree_util.tree_map(
                    lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                    batch)
                (grads, loss_sum, _), _ = jax.lax.scan(
                    micro_body,
                    (zeros, jnp.zeros([], jnp.float32), step_rng),
                    micro_batches)
            else:
                (grads, loss_sum, _), _ = micro_body(
                    (zeros, jnp.zeros([], jnp.float32), step_rng), batch)
            mean_loss = loss_sum / gas
            grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
            return grads, mean_loss

        def compressed_grads(params, batch, step_rng, scale_state, step,
                             comm_error):
            """Per-rank grads + 1-bit error-feedback allreduce over 'data'
            inside shard_map — the wire carries packed uint8 signs + one
            f32 scale per leaf (ref: nccl.py:52 compressed_allreduce)."""
            from deepspeed_tpu.parallel.compressed import (
                compressed_allreduce_local)
            err_leaves, err_treedef = jax.tree_util.tree_flatten(comm_error)

            def local_fn(params, batch, comm_error_leaves):
                # decorrelate per-rank dropout/rng
                local_rng = jax.random.fold_in(
                    step_rng, jax.lax.axis_index("data"))
                local_grads, local_loss = accum_grads(
                    params, batch, local_rng, scale_state, step)
                if fp16:
                    local_grads = ls.unscale_grads(local_grads, scale_state)
                    # overflow must be caught BEFORE compression — an inf
                    # gradient would poison the error residual (inf - inf)
                    # for every later step (ref checks overflow pre-compress)
                    ovf = jax.lax.pmax(
                        ls.has_overflow(local_grads).astype(jnp.float32),
                        "data") > 0
                else:
                    ovf = jnp.asarray(False)
                g_leaves = jax.tree_util.tree_leaves(local_grads)
                outs, new_errs = [], []
                for g, e in zip(g_leaves, comm_error_leaves):
                    g = jnp.where(ovf, jnp.zeros_like(g), g)
                    avg, ne = compressed_allreduce_local(
                        g, e[0], axis="data")
                    outs.append(avg)
                    new_errs.append(jnp.where(ovf, e[0], ne)[None])
                loss = jax.lax.pmean(local_loss, "data")
                return tuple(outs), tuple(new_errs), loss, ovf

            gspecs = tuple(P() for _ in err_leaves)
            espec = tuple(P("data") for _ in err_leaves)
            pspec = jax.tree_util.tree_map(lambda _: P(), params)
            bspec = jax.tree_util.tree_map(lambda _: P("data"), batch)
            out = shard_map(
                local_fn, mesh=mesh,
                in_specs=(pspec, bspec, espec),
                out_specs=(gspecs, espec, P(), P()),
                axis_names={"data"}, check_vma=False)(
                    params, batch, tuple(err_leaves))
            g_flat, e_flat, mean_loss, ovf = out
            grads = jax.tree_util.tree_unflatten(err_treedef, list(g_flat))
            new_error = jax.tree_util.tree_unflatten(err_treedef,
                                                     list(e_flat))
            return grads, mean_loss, new_error, ovf

        mem_eff = self.memory_efficient_bf16

        def step_fn(state: TrainState, batch: PyTree):
            rng, step_rng = jax.random.split(state.rng)

            if compressed:
                grads, mean_loss, new_comm_error, overflow = compressed_grads(
                    state.params, batch, step_rng, state.scale_state,
                    state.step, state.comm_error)
            else:
                grads, mean_loss = accum_grads(
                    state.params, batch, step_rng, state.scale_state,
                    state.step)
                new_comm_error = state.comm_error
                # ---- unscale + overflow check (fp16) ----
                if fp16:
                    grads = ls.unscale_grads(grads, state.scale_state)
                    overflow = ls.has_overflow(grads)
                else:
                    overflow = jnp.asarray(False)

            gnorm = global_norm(grads)
            if clip > 0.0:
                grads = clip_by_global_norm(grads, clip, norm=gnorm)

            # ---- optimizer update with overflow skip (lax.cond) ----
            def do_step(operands):
                g, os_, p = operands
                updates, new_os = optimizer.update(g, os_, p)
                if mem_eff:
                    # bf16 masters: stochastic-rounding add so sub-ulp
                    # updates land in expectation (ops/adam.py)
                    from deepspeed_tpu.ops.adam import sr_apply_updates
                    new_p = sr_apply_updates(
                        p, updates, jax.random.fold_in(step_rng, 0x5eed))
                else:
                    new_p = optax.apply_updates(p, updates)
                return new_os, new_p

            def skip_step(operands):
                _, os_, p = operands
                return os_, p

            new_opt_state, new_params = jax.lax.cond(
                overflow, skip_step, do_step,
                (grads, state.opt_state, state.params))

            new_scale = ls.update(
                state.scale_state, overflow,
                dynamic=self.dynamic_loss_scale and fp16,
                scale_window=cfg.fp16.loss_scale_window,
                min_scale=cfg.fp16.min_loss_scale,
                max_hysteresis=cfg.fp16.hysteresis)

            new_state = TrainState(
                step=state.step + jnp.where(overflow, 0, 1),
                params=new_params,
                opt_state=new_opt_state,
                scale_state=new_scale,
                rng=rng,
                comm_error=new_comm_error)
            metrics = {
                "loss": mean_loss,
                "grad_norm": gnorm,
                "lr": jnp.asarray(self.lr_schedule(state.step), jnp.float32),
                "loss_scale": new_scale.loss_scale,
                "overflow": overflow,
            }
            return new_state, metrics

        state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()),
            params=self.param_shardings,
            opt_state=self.opt_shardings,
            scale_state=jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P()), self.state.scale_state),
            rng=NamedSharding(self.mesh, P()),
            comm_error=(self._comm_error_shardings()
                        if self.compressed_comm else None))
        metrics_sh = NamedSharding(self.mesh, P())

        self._state_shardings = state_shardings
        self._batch_shard_leaf = mesh_lib.batch_sharding(self.mesh)
        return jax.jit(
            step_fn,
            in_shardings=(state_shardings, None),  # batch: committed by _shard_batch
            out_shardings=(state_shardings, metrics_sh),
            donate_argnums=(0,) if donate_state else ())

    def _build_grad_step(self):
        """Grad-only program for the offload path: forward+backward+clip on
        device; the optimizer update happens on host (runtime/zero/offload)."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        clip = cfg.gradient_clipping
        fp16 = self.fp16_enabled
        compute_dtype = self.compute_dtype
        loss_fn = self.loss_fn
        has_aux = self.has_aux
        prescale = cfg.prescale_gradients
        predivide = cfg.gradient_predivide_factor

        # MoQ + PLD compose with offload exactly as with the fused step:
        # both only transform the in-jit FORWARD (fake-quantized compute
        # params / theta-scheduled layer drop) — the host optimizer never
        # sees them (ref: engine.py:1789-1800 + :1542 compose with
        # cpu_offload the same way)
        quant_fn = self.quantizer.make_transform(
            step_at_build=self.global_steps - self.skipped_steps) \
            if (self.quantizer is not None and self.quantizer.active) else None
        pld_cfg = cfg.pld if cfg.pld.enabled else None

        def micro_loss(params, micro_batch, rng, scale_state, step):
            cparams = _cast_tree(params, compute_dtype)
            if quant_fn is not None:
                rng, qr = jax.random.split(rng)
                cparams = quant_fn(cparams, qr, step)
            micro_batch = _cast_tree(micro_batch, compute_dtype)
            if pld_cfg is not None and isinstance(micro_batch, dict):
                from deepspeed_tpu.runtime.progressive_layer_drop import (
                    PLD_THETA_KEY, theta_schedule)
                micro_batch = dict(micro_batch)
                micro_batch[PLD_THETA_KEY] = theta_schedule(
                    step, pld_cfg.theta, pld_cfg.gamma)
            out = loss_fn(cparams, micro_batch, rng)
            loss, aux = out if has_aux else (out, {})
            scaled = ls.scale_loss(loss.astype(jnp.float32), scale_state) \
                if fp16 else loss
            return scaled.astype(jnp.float32), (loss, aux)

        grad_fn = jax.grad(micro_loss, has_aux=True)

        def gstep(params, batch, rng, scale_state, step):
            rng, step_rng = jax.random.split(rng)

            def micro_body(carry, micro):
                grads_acc, loss_acc, r = carry
                r, mr = jax.random.split(r)
                g, (loss, _aux) = grad_fn(params, micro, mr, scale_state,
                                          step)
                if prescale and predivide != 1.0:
                    g = jax.tree_util.tree_map(lambda x: x / predivide, g)
                grads_acc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32), grads_acc, g)
                return (grads_acc, loss_acc + loss.astype(jnp.float32), r), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if gas > 1:
                micro_batches = jax.tree_util.tree_map(
                    lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                    batch)
                (grads, loss_sum, _), _ = jax.lax.scan(
                    micro_body, (zeros, jnp.zeros([], jnp.float32), step_rng),
                    micro_batches)
            else:
                (grads, loss_sum, _), _ = micro_body(
                    (zeros, jnp.zeros([], jnp.float32), step_rng), batch)

            grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
            if fp16:
                grads = ls.unscale_grads(grads, scale_state)
                overflow = ls.has_overflow(grads)
            else:
                overflow = jnp.asarray(False)
            gnorm = global_norm(grads)
            if clip > 0.0:
                grads = clip_by_global_norm(grads, clip, norm=gnorm)
            new_scale = ls.update(
                scale_state, overflow,
                dynamic=self.dynamic_loss_scale and fp16,
                scale_window=cfg.fp16.loss_scale_window,
                min_scale=cfg.fp16.min_loss_scale,
                max_hysteresis=cfg.fp16.hysteresis)
            metrics = {"loss": loss_sum / gas, "grad_norm": gnorm,
                       "overflow": overflow,
                       "loss_scale": new_scale.loss_scale}
            return grads, rng, new_scale, metrics

        rep = NamedSharding(self.mesh, P())
        scale_sh = jax.tree_util.tree_map(lambda _: rep,
                                          self.state.scale_state)
        self._state_shardings = TrainState(
            step=rep, params=self.param_shardings, opt_state=None,
            scale_state=scale_sh, rng=rep)
        self._batch_shard_leaf = mesh_lib.batch_sharding(self.mesh)
        return jax.jit(
            gstep,
            in_shardings=(self.param_shardings, None, rep, scale_sh, rep),
            out_shardings=(self.param_shardings, rep, scale_sh, rep))

    def _offload_train_batch(self, batch: PyTree) -> Dict[str, jnp.ndarray]:
        grads, rng, new_scale, metrics = self._grad_step(
            self.state.params, batch, self.state.rng, self.state.scale_state,
            jnp.asarray(int(self.state.step), jnp.int32))
        self.state.rng = rng
        self.state.scale_state = new_scale
        if self.dpu_enabled:
            # delayed param update (ZeRO-Offload DPU): the grad program
            # for THIS batch was dispatched with the previous params;
            # install the overlapped update from the last step, then hand
            # this step's grads to the worker — the host Adam runs behind
            # the device's next forward/backward at one step of staleness
            if self._dpu_pending is not None:
                self.state.params = self._dpu_pending.result()
                self.state.step = self.state.step + 1
            lr = float(self.lr_schedule(int(self.state.step)))
            self._dpu_pending = self._dpu_executor.submit(
                self.host_optimizer.step, grads, lr)
        elif not bool(metrics["overflow"]):
            # pipelined shard-wise d2h -> host native optimizer -> h2d;
            # the returned tree is already placed on the mesh
            # (ref: stage_1_and_2.py:1005,1725)
            self.state.params = self.host_optimizer.step(
                grads, lr=float(self.lr_schedule(int(self.state.step))))
            self.state.step = self.state.step + 1
        metrics["lr"] = jnp.asarray(self.lr_schedule(int(self.state.step)),
                                    jnp.float32)
        return metrics

    def flush_delayed_update(self) -> None:
        """Join a pending DPU host step (call before checkpointing or
        evaluation so the installed params are current)."""
        if getattr(self, "_dpu_pending", None) is not None:
            self.state.params = self._dpu_pending.result()
            self.state.step = self.state.step + 1
            self._dpu_pending = None

    def _shard_batch(self, batch: PyTree) -> PyTree:
        """Place a host batch on the mesh: leading dim over the dp axes,
        token dim over 'sequence' when sequence parallelism is active."""
        shardings = jax.tree_util.tree_map(self._batch_shard_leaf, batch)
        return jax.device_put(batch, shardings)

    def _build_eval_step(self):
        compute_dtype = self.compute_dtype
        # 1F1B pipeline losses run fwd+bwd eagerly inside their forward
        # (custom_vjp) — they attach an eval-safe GPipe companion
        loss_fn = getattr(self.loss_fn, "eval_fn", None) or self.loss_fn
        has_aux = self.has_aux

        def eval_fn(params, batch, rng):
            cparams = _cast_tree(params, compute_dtype)
            out = loss_fn(cparams, batch, rng)
            return out if has_aux else (out, {})

        return jax.jit(
            eval_fn,
            in_shardings=(self.param_shardings, None, None),
            out_shardings=NamedSharding(self.mesh, P()))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def start_trace(self, log_dir: str, steps: int = 1) -> None:
        """Capture an XPlane trace of the next ``steps`` train_batch calls
        into ``log_dir`` (TensorBoard/xprof readable) — the runtime analog
        of the reference's NVTX+nsight workflow (ref: utils/nvtx.py:4,
        docs/_tutorials/pytorch-profiler.md). See utils/trace.py."""
        jax.block_until_ready(self.state.params)  # trace only the window
        jax.profiler.start_trace(log_dir)
        self._trace_steps_left = max(1, int(steps))

    def train_batch(self, batch: PyTree) -> Dict[str, jnp.ndarray]:
        """One full optimizer step over a global batch
        (leading dim == train_batch_size). Fuses the reference's
        forward+backward+step triple into one XLA program."""
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        if self.curriculum_scheduler is not None:
            difficulty = self.curriculum_scheduler.update_difficulty(
                self.global_steps + 1)
            batch = self._apply_curriculum(batch, difficulty)
        if self.progressive_layer_drop is not None:
            # keyed on applied steps, matching the in-jit theta_schedule
            # even when fp16 overflow skips steps; computed host-side
            # (global - skipped) to avoid syncing on state.step
            self.progressive_layer_drop.update_state(
                self.global_steps - self.skipped_steps)
        batch = self._shard_batch(batch)
        profiling_now = (self.config.flops_profiler.enabled
                         and not self.offload_enabled
                         and self.global_steps + 1 ==
                         self.config.flops_profiler.profile_step)
        if profiling_now:
            # drain queued prior steps so the timed window is exactly
            # this step (set profile_step >= 2 to exclude compile time)
            jax.block_until_ready(self.state.params)
        t0 = time.perf_counter()
        from deepspeed_tpu.utils.trace import annotation
        # mesh in context: models can pin activation layouts with bare
        # PartitionSpecs (gpt.py scan-carry constraint) during tracing
        # jax.set_mesh is the 0.5+ spelling; older jax enters the Mesh
        # itself as the context manager to the same effect
        with annotation("ds.train_batch"), \
                (jax.set_mesh(self.mesh) if hasattr(jax, "set_mesh")
                 else self.mesh):
            if self.offload_enabled:
                metrics = self._offload_train_batch(batch)
            else:
                self.state, metrics = self._train_step(self.state, batch)
        if getattr(self, "_trace_steps_left", 0) > 0:
            self._trace_steps_left -= 1
            if self._trace_steps_left == 0:
                jax.block_until_ready(metrics["loss"])
                jax.profiler.stop_trace()
        if profiling_now:
            # block only on the profiled step — every other step keeps
            # async dispatch so the host can run ahead
            jax.block_until_ready(metrics["loss"])
        self._last_step_duration = time.perf_counter() - t0
        if profiling_now:
            self._run_flops_profile(batch)
        self.timers(TRAIN_BATCH_TIMER).stop()
        self.tput_timer.stop(global_step=True)
        if self.quantizer is not None:
            self._take_quantize_step(batch, bool(metrics["overflow"]))
        self.global_steps += 1
        self.micro_steps += self.config.gradient_accumulation_steps
        self.global_samples += self.config.train_batch_size
        # Overflow (and therefore step-skipping) only exists under fp16 loss
        # scaling; in bf16/fp32 the in-jit flag is constant False. Reading it
        # host-side would force a device sync every step — on a remote-dispatch
        # TPU runtime that is a full RPC roundtrip that serializes the
        # pipeline (the reference pays the same sync in its per-step
        # check_overflow allreduce, stage_1_and_2.py:1640; we only pay it when
        # the feature is actually on).
        if self.fp16_enabled and bool(metrics["overflow"]):
            self.skipped_steps += 1
        if self.monitor.enabled:
            # scalar names mirror the reference's tensorboard tags
            # (ref: engine.py:1656-1666, :1889-1917). Buffer the device
            # scalars and convert only at flush boundaries — float() every
            # step would block on the device and defeat async dispatch.
            self._monitor_buffer.append(
                (self.global_samples, metrics["loss"], metrics["lr"],
                 metrics["loss_scale"]))
            if (self.global_steps % self.config.steps_per_print == 0
                    or len(self._monitor_buffer) >= 64):
                self._flush_monitor_buffer()
        if self.global_steps % self.config.steps_per_print == 0:
            self._report_progress(metrics)
        return metrics

    def _flush_monitor_buffer(self):
        buffered, self._monitor_buffer = self._monitor_buffer, []
        if not buffered:
            return
        # ONE device_get for the whole buffer: three float() per buffered
        # step would issue 3*len(buffered) blocking transfers (each a full
        # RPC roundtrip on a remote-dispatch runtime); fetching the pytree
        # at once pays a single sync for the flush
        scalars = jax.device_get([(loss, lr, scale)
                                  for _, loss, lr, scale in buffered])
        events = []
        for (samples, *_), (loss, lr, scale) in zip(buffered, scalars):
            events.extend([
                ("Train/Samples/train_loss", float(loss), samples),
                ("Train/Samples/lr", float(lr), samples),
                ("Train/Samples/loss_scale", float(scale), samples),
            ])
        self.monitor.write_scalars(events)

    def set_flops_per_batch(self, flops: float) -> None:
        """Analytic per-batch flops override for the profiler. XLA's
        cost analysis counts a lax.scan body once, so scan-over-layers
        models (our GPT) undercount; pass e.g.
        ``gpt.train_flops_per_token(cfg, S) * tokens_per_batch``."""
        self._flops_per_batch = flops

    def _run_flops_profile(self, batch: PyTree) -> None:
        """One-step flops profile (ref: engine.py:1535-1540 triggers the
        FlopsProfiler for flops_profiler.profile_step). Static XLA cost
        analysis of the already-compiled train step + this step's
        measured wall time → achieved TFLOPS / MFU."""
        from deepspeed_tpu.profiling.flops_profiler import (
            analyze_compiled, device_peak_flops)
        try:
            cost = analyze_compiled(self._train_step, self.state, batch)
        except Exception as e:  # pragma: no cover - backend-dependent
            log_dist(f"flops profile unavailable: {e}", ranks=[0])
            return
        override = getattr(self, "_flops_per_batch", None)
        if override:
            cost = dict(cost, flops=float(override))
        dur = max(self._last_step_duration, 1e-9)
        n_params = count_parameters(self.state.params)
        achieved = cost["flops"] / dur
        peak = device_peak_flops()
        n_dev = max(1, len(jax.devices()))
        lines = [
            "", "-" * 64, "DeepSpeed-TPU Flops Profiler (train step)",
            "-" * 64,
            f"profile step:        {self.global_steps + 1}",
            f"params:              {n_params / 1e6:.2f} M",
            f"step flops:          {cost['flops'] / 1e12:.3f} TF",
            f"HBM bytes accessed:  {cost['bytes_accessed'] / 1e9:.2f} GB",
            f"step latency:        {dur * 1e3:.2f} ms",
            f"achieved throughput: {achieved / 1e12:.2f} TFLOPS "
            f"({achieved / n_dev / 1e12:.2f}/device)",
            f"samples/sec:         {self.config.train_batch_size / dur:.1f}",
        ]
        if peak:
            lines.append(
                f"MFU:                 {achieved / (peak * n_dev) * 100:.1f}%")
        lines.append("-" * 64)
        log_dist("\n".join(lines), ranks=[0])
        out = self.config.flops_profiler.output_file
        if out:
            with open(out, "w") as f:
                f.write("\n".join(lines) + "\n")

    # batch-dict keys whose axis 1 is a sequence dimension; other leaves
    # (class labels, masks with sequence elsewhere, ...) are left alone
    CURRICULUM_SEQ_KEYS = ("tokens", "input_ids", "targets", "labels",
                           "loss_mask", "attention_mask", "position_ids")

    def set_curriculum_transform(self, fn) -> None:
        """Override the seqlen truncation with a custom
        ``fn(batch, difficulty) -> batch`` (required for non-dict
        batches or models whose sequence axis is not axis 1)."""
        self._curriculum_transform = fn

    def _apply_curriculum(self, batch: PyTree, difficulty: int) -> PyTree:
        """seqlen curriculum: truncate the sequence axis (axis 1) of the
        well-known token/label keys of a dict batch. Each distinct
        difficulty is one XLA program — difficulty_step bounds the
        recompile count (ref: the fwd-kwarg seqlen injection,
        engine.py:1548-1554)."""
        custom = getattr(self, "_curriculum_transform", None)
        if custom is not None:
            return custom(batch, difficulty)
        if self.config.curriculum.curriculum_type != "seqlen":
            return batch
        if not isinstance(batch, dict):
            raise TypeError(
                "seqlen curriculum needs a dict batch with token keys "
                f"{self.CURRICULUM_SEQ_KEYS}; for other batch layouts "
                "call engine.set_curriculum_transform(fn)")

        def trunc(x):
            if hasattr(x, "ndim") and x.ndim >= 2 and x.shape[1] > difficulty:
                return x[:, :difficulty]
            return x

        return {k: (trunc(v) if k in self.CURRICULUM_SEQ_KEYS else v)
                for k, v in batch.items()}

    def _take_quantize_step(self, batch, overflow: bool) -> None:
        """Post-step MoQ hook: optionally refresh block eigenvalues at a
        GAS boundary, advance the bit schedule, and recompile the train
        step when a precision switch happened (ref: engine.py:1789-1800;
        the quantization itself runs inside the jitted step, see
        _build_train_step)."""
        if self.eigenvalue is not None and self.global_steps % \
                self.eigenvalue.gas_boundary_resolution == 0 and \
                self.quantizer.any_precision_switch():
            # one micro-batch only: the HVP costs ~2x a backward pass and
            # must fit in the same HBM the gas-split train step fits in
            micro_bs = self.config.train_micro_batch_size_per_gpu * \
                self.dp_world_size

            def slice_leaf(x):
                # only array leaves with a leading batch axis can be
                # micro-sliced; scalars/rank-0 leaves (and non-addressable
                # multi-host shards, which cannot be indexed host-side)
                # pass through unchanged
                if not hasattr(x, "ndim") or x.ndim < 1 or \
                        x.shape[0] < micro_bs:
                    return x
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return x
                return x[:micro_bs]

            micro = jax.tree_util.tree_map(slice_leaf, batch)
            self.block_eigenvalue = self.eigenvalue.compute_eigenvalue(
                self._eigenvalue_loss, self.state.params, micro,
                self.state.rng)
        switched = self.quantizer.advance(
            overflow=overflow,
            eigenvalue_enabled=self.eigenvalue is not None,
            block_eigenvalue=self.block_eigenvalue)
        if switched:
            if self.offload_enabled:
                self._grad_step = self._build_grad_step()
            else:
                self._train_step = self._build_train_step(self._donate_state)

    def destroy(self) -> None:
        """Flush and release engine-owned sinks (monitor/TB writer) and
        any pending delayed param update + its worker thread."""
        self.flush_delayed_update()
        if getattr(self, "_dpu_executor", None) is not None:
            self._dpu_executor.shutdown(wait=True)
        self._flush_monitor_buffer()
        self.monitor.close()

    # familiarity wrappers --------------------------------------------
    def __call__(self, batch):
        return self.forward(batch)

    def forward(self, batch, rng: Optional[jax.Array] = None):
        """Inference/eval forward (loss only; ref: engine.py:1523)."""
        self.flush_delayed_update()
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        loss, _ = self._eval_step(self.state.params, self._shard_batch(batch), rng)
        return loss

    def eval_batch(self, batch, rng: Optional[jax.Array] = None):
        self.flush_delayed_update()
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        return self._eval_step(self.state.params, self._shard_batch(batch), rng)

    def backward(self, loss):  # pragma: no cover - API parity shim
        raise RuntimeError(
            "On TPU the forward/backward/step triple is fused into "
            "engine.train_batch(batch); call that instead "
            "(see SURVEY.md §3.2 for the mapping).")

    def step(self):  # pragma: no cover - API parity shim
        raise RuntimeError("see DeepSpeedEngine.backward — use train_batch().")

    # properties ------------------------------------------------------
    @property
    def params(self):
        self.flush_delayed_update()
        return self.state.params

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    @property
    def zero_optimization_stage(self):
        return self.config.zero.stage

    def zero_optimization(self):
        return self.config.zero.enabled

    def get_global_grad_norm(self):
        return None  # available in train metrics

    def get_lr(self):
        return [float(self.lr_schedule(int(self.state.step)))]

    def get_loss_scale(self):
        return float(self.state.scale_state.loss_scale)

    def _report_progress(self, metrics):
        lr = float(metrics["lr"])
        loss = float(metrics["loss"])
        log_dist(
            f"step={self.global_steps}, skipped={self.skipped_steps}, "
            f"lr={lr:.3e}, loss={loss:.4f}, "
            f"loss_scale={float(metrics['loss_scale']):.1f}", ranks=[0])

    # checkpointing ---------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True):
        self.flush_delayed_update()
        from deepspeed_tpu.runtime.checkpointing import save_checkpoint
        return save_checkpoint(self, save_dir, tag=tag,
                               client_state=client_state or {},
                               save_latest=save_latest)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        strict: bool = False):
        # join-and-DISCARD any in-flight DPU update: the worker must not
        # mutate host masters during restore, and its pre-load result
        # must never overwrite the restored weights
        if getattr(self, "_dpu_pending", None) is not None:
            self._dpu_pending.result()
            self._dpu_pending = None
        from deepspeed_tpu.runtime.checkpointing import load_checkpoint
        return load_checkpoint(self, load_dir, tag=tag,
                               load_optimizer_states=load_optimizer_states,
                               strict=strict)

    def consolidated_16bit_state_dict(self):
        """Gather full (unsharded) compute-dtype params on host
        (ref: engine.py:3060 _zero3_consolidated_16bit_state_dict)."""
        # the gather-and-cast program is cached on the engine: a fresh
        # jit(lambda) per call would recompile every checkpoint save
        # (dslint DS002)
        fn = getattr(self, "_consolidate_16bit_fn", None)
        if fn is None:
            def _gather_cast(p):
                return _cast_tree(p, self.compute_dtype)
            fn = jax.jit(_gather_cast,
                         out_shardings=jax.tree_util.tree_map(
                             lambda _: NamedSharding(self.mesh, P()),
                             self.state.params))
            self._consolidate_16bit_fn = fn
        return jax.device_get(fn(self.state.params))

    def module_state_dict(self):
        """The param pytree (the reference's module.state_dict analog,
        ref: engine.py:3107)."""
        return self.state.params

    def save_16bit_model(self, save_dir: str,
                         save_filename: str = "model_weights.npz") -> bool:
        """Consolidate the (possibly ZeRO-3-sharded) weights and save ONE
        flat compute-dtype npz (ref: engine.py:3136 save_16bit_model —
        there a torch .bin; here a numpy archive with path-joined keys;
        bf16 leaves are stored as uint16 bit patterns with a dtype
        manifest since npz has no bf16). Load with
        ``runtime.checkpointing.load_16bit_model``."""
        self.flush_delayed_update()
        from deepspeed_tpu.runtime.checkpointing import write_16bit_model
        write_16bit_model(self.consolidated_16bit_state_dict(),
                          save_dir, save_filename)
        return True
