"""LoRA fine-tuning: low-rank adapters on the dense projections.

Beyond the reference surface (v0.6.4 predates LoRA): freeze the base
model, train rank-r adapters A [in, r], B [r, out] per projection with
effective weight W0 + (alpha/r) * A @ B. The forward pass takes the
low-rank path (gpt._dense) — the dense delta is never materialized —
and the optimizer holds state ONLY for adapter leaves, so fine-tuning a
bf16 7B-class model needs megabytes of optimizer state instead of
gigabytes.

Engine integration is pure optax: ``lora_optimizer(base, params)``
wraps the configured transform in ``optax.multi_transform`` with
``set_to_zero`` on frozen leaves, and ``deepspeed_tpu.initialize(...,
optimizer=...)`` accepts it unchanged. ``merge_lora`` folds the
adapters into the kernels for serving (composes with int8 quantization:
merge first, then quantize).
"""

from typing import Iterable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

DEFAULT_TARGETS = ("qkv", "attn_out", "mlp_in", "mlp_gate", "mlp_out")


def add_lora(params, rng, rank: int = 8, alpha: float = 16.0,
             targets: Iterable[str] = DEFAULT_TARGETS):
    """Return params with lora_a/lora_b/lora_scale added to every
    targeted dense entry (entries missing in the model — e.g. mlp_gate
    on a gelu dialect — are skipped). A ~ N(0, 1/rank), B = 0, so the
    adapted model starts EXACTLY at the base model."""
    targets = set(targets)
    out = dict(params)
    out["block"] = {**params["block"]}

    def adapt(entry, key):
        w = entry["kernel"]
        fan_in, fan_out = w.shape[-2], w.shape[-1]
        lead = w.shape[:-2]
        a = jax.random.normal(key, lead + (fan_in, rank),
                              jnp.float32) / np.sqrt(rank)
        entry = dict(entry)
        entry["lora_a"] = a
        entry["lora_b"] = jnp.zeros(lead + (rank, fan_out), jnp.float32)
        # carries the stacked-layer leading dim so lax.scan over the
        # block tree can slice it like every other leaf
        entry["lora_scale"] = jnp.full(lead, alpha / rank, jnp.float32)
        return entry

    block = out["block"]
    keys = jax.random.split(rng, max(len(targets), 1))
    for i, name in enumerate(sorted(targets)):
        if name in block and "kernel" in block[name]:
            block[name] = adapt(block[name], keys[i])
    return out


def lora_label_tree(params):
    """'train' on lora_a/lora_b leaves, 'freeze' everywhere else
    (incl. lora_scale — it is a hyperparameter, not a weight)."""
    def label(path, _leaf):
        names = {getattr(k, "key", getattr(k, "name", "")) for k in path}
        return ("train" if ("lora_a" in names or "lora_b" in names)
                else "freeze")
    return jax.tree_util.tree_map_with_path(label, params)


def lora_optimizer(base: optax.GradientTransformation, params):
    """Wrap the configured optimizer so ONLY adapter leaves train;
    frozen leaves get zero updates and (with optax's masked internals)
    no optimizer state."""
    return optax.multi_transform(
        {"train": base, "freeze": optax.set_to_zero()},
        lora_label_tree(params))


def merge_lora(params):
    """Fold each adapter into its kernel (W0 + scale * A @ B) and strip
    the lora keys — the serving form (quantize AFTER merging)."""
    def walk(tree):
        if isinstance(tree, dict):
            if "lora_a" in tree:
                out = {k: v for k, v in tree.items()
                       if not k.startswith("lora_")}
                delta = jnp.einsum(
                    "...ir,...ro->...io", tree["lora_a"],
                    tree["lora_b"]) * tree["lora_scale"][..., None, None]
                out["kernel"] = (tree["kernel"] +
                                 delta.astype(tree["kernel"].dtype))
                return out
            return {k: walk(v) for k, v in tree.items()}
        return tree
    return walk(params)


def count_trainable(params) -> Tuple[int, int]:
    """(adapter params, total params) — the memory-story numbers."""
    labels = lora_label_tree(params)
    train = sum(x.size for x, lab in
                zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(labels)) if lab == "train")
    total = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return train, total


def adapter_state_dict(params):
    """Only the adapter leaves, keyed by '/'-joined path — the whole
    fine-tune in kilobytes-to-megabytes (the base model ships
    separately, like every LoRA ecosystem expects). Leaves are stored
    fp32: lossless from bf16 (npz cannot represent bf16 — see
    checkpointing.write_16bit_model's workaround; adapters are small
    enough that widening beats a bit-pattern manifest)."""
    out = {}

    def walk(tree, prefix):
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(v, prefix + (k,))
        elif prefix and prefix[-1].startswith("lora_"):
            out["/".join(prefix)] = np.asarray(
                jnp.asarray(tree).astype(jnp.float32))

    walk(params, ())
    return out


def save_adapter(params, path: str):
    """Write the adapters (and only the adapters) to ``path`` (.npz)."""
    np.savez(path, **adapter_state_dict(params))


def load_adapter(params, path: str):
    """Return ``params`` with the adapters from ``path`` attached —
    ``params`` may be the bare base model (entries gain lora keys) or an
    already-adapted tree (entries are overwritten). Shapes must match
    the base kernels; a mismatched file raises.

    Note: ``deepspeed_tpu.initialize`` donates its model_parameters
    buffers — attach adapters to a FRESHLY constructed/loaded base (or
    to ``engine.module_state_dict()``), not to a tree previously handed
    to an engine."""
    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in params.items()}
    with np.load(path) as data:
        for flat in data.files:
            keys = flat.split("/")
            entry_keys, leaf = keys[:-1], keys[-1]
            node = out
            for k in entry_keys:
                if not isinstance(node, dict) or k not in node:
                    raise KeyError(
                        f"adapter path {flat!r} has no matching entry in "
                        f"the base params (at {k!r})")
                node[k] = (dict(node[k]) if isinstance(node[k], dict)
                           else node[k])
                node = node[k]
            if not isinstance(node, dict):
                raise KeyError(
                    f"adapter path {flat!r} does not address a dense "
                    f"entry in the base params")
            val = data[flat]
            # int8-served bases carry "q" (kernel's shape) instead
            kern = node.get("kernel", node.get("q"))
            if kern is not None and leaf in ("lora_a", "lora_b"):
                ok = (val.shape[:-1] == kern.shape[:-1]
                      if leaf == "lora_a"
                      else (val.shape[:-2] == kern.shape[:-2]
                            and val.shape[-1] == kern.shape[-1]))
                if not ok:
                    raise ValueError(
                        f"adapter {flat!r} shape {val.shape} does not "
                        f"match the base kernel's {kern.shape}")
            node[leaf] = jnp.asarray(val)
    return out
