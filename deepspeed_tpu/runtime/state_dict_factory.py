"""Checkpoint loader/resharder for Megatron-style TP checkpoints.

Capability match for the reference's state-dict factory
(ref: deepspeed/runtime/state_dict_factory.py:17 SDLoaderFactory,
:195 MegatronSDLoader): load per-TP-rank checkpoint files and
merge/split them to a *different* inference model-parallel degree,
with layout-aware handling of fused query/key/value weights across the
three historical Megatron QKV formats.

TPU-native: tensors are manipulated as numpy (ready for jax
device_put with TP shardings); torch is used only to deserialize the
reference's .pt files (torch-cpu is in the image). Our own
checkpoints never need this — orbax stores one logical array that any
mesh reshape can reload — so this exists to migrate reference-world
checkpoints in.
"""

import collections
import copy
import json
import os
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
from deepspeed_tpu.utils.logging import logger

AUTO_MODULE_KEY = "auto"


def _load_ckpt_file(path: str) -> Dict:
    """Deserialize one shard file: .pt (torch) or .npz."""
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=True) as z:
            data = {k: z[k] for k in z.files}
        if "__sd__" in data:  # pickled nested dict
            return data["__sd__"].item()
        return data
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=False)

    def to_np(x):
        if isinstance(x, torch.Tensor):
            return x.detach().to(torch.float32).numpy() \
                if x.dtype in (torch.float16, torch.bfloat16) \
                else x.detach().numpy()
        if isinstance(x, dict):
            return {k: to_np(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(to_np(v) for v in x)
        return x
    return to_np(sd)


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file):
        """(ref: state_dict_factory.py:18) json with type/checkpoints/
        version keys (path or dict)."""
        data = json_file
        if not isinstance(data, dict):
            with open(json_file) as f:
                data = json.load(f)
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", version=None):
        if sd_type == "Megatron":
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"{sd_type} checkpoint type is not supported")


class SDLoaderBase(ABC):
    def __init__(self, ckpt_list: List[str], version):
        self.module_key = None
        self.ckpt_list = ckpt_list
        self.check_ckpt_list()
        self.version = version

    def load(self, mp_world_size: int, mp_rank: int,
             module_key: str = AUTO_MODULE_KEY,
             is_pipe_parallel: bool = False,
             quantize: bool = False, quantize_bits: int = 8,
             quantize_groups: int = 64,
             mlp_extra_grouping: bool = True
             ) -> Tuple[str, Dict, Tuple[Optional[np.ndarray], int]]:
        """(ref: state_dict_factory.py:41) direct / merge / split by
        comparing checkpoint count with the target MP degree."""
        self.module_key = module_key
        num_ckpt = len(self.ckpt_list)
        idx = mp_rank * num_ckpt // mp_world_size
        if is_pipe_parallel and module_key is not None and \
                mp_world_size != num_ckpt:
            mp_world_size = num_ckpt
            idx = 0
        load_path = self.ckpt_list[idx]

        merge_count = 1
        if num_ckpt == mp_world_size:
            assert os.path.exists(load_path), load_path
            sd = _load_ckpt_file(load_path)
            if quantize:
                quantizer = WeightQuantization(
                    mlp_extra_grouping=mlp_extra_grouping,
                    mp_size=mp_world_size)
                sd_module, all_scales = self.sd_quantize(
                    quantizer, self.get_module(sd), quantize_bits,
                    quantize_groups)
                self.set_module(sd, sd_module)
            else:
                all_scales = None
        elif num_ckpt > mp_world_size:
            sd, all_scales, merge_count = self.merge_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        else:
            sd, all_scales = self.split_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        return load_path, sd, (all_scales, merge_count)

    def sd_quantize(self, quantizer, sd_module, quantize_bits, groups):
        """Quantize the qkv/dense/mlp weights of a module sd
        (ref: weight_quantizer.py sd_quantize_megatron)."""
        keys = list(sd_module.keys())
        import jax.numpy as jnp
        for key in keys:
            if any(t in key for t in ("attention.dense.weight",
                                      "query_key_value.weight",
                                      "mlp.dense_4h_to_h.weight",
                                      "mlp.dense_h_to_4h.weight")):
                [q] = quantizer.Quantize(
                    [jnp.asarray(sd_module[key])], quantize_bits, groups,
                    key=key)
                sd_module[key] = np.asarray(q)
        all_scales = np.asarray(quantizer.merge_scales()) \
            if quantizer.qkv_scales else None
        return sd_module, all_scales

    def get_merge_state_dicts(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert num_ckpt % mp_world_size == 0, \
            "Invalid checkpoints and world size for sd merge"
        num_to_merge = num_ckpt // mp_world_size
        ckpt_list = self.ckpt_list[num_to_merge * mp_rank:
                                   num_to_merge * (mp_rank + 1)]
        logger.info(f"mp_rank: {mp_rank}, ckpt_list: {ckpt_list}")
        return [_load_ckpt_file(c) for c in ckpt_list]

    def get_split_state_dict(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert mp_world_size % num_ckpt == 0, \
            "Invalid checkpoints and world size for sd split"
        num_to_split = mp_world_size // num_ckpt
        ckpt_index = mp_rank // num_to_split
        ckpt_offset = mp_rank % num_to_split
        logger.info(f"mp_rank: {mp_rank}, ckpt: {ckpt_index}, "
                    f"offset: {ckpt_offset}")
        return _load_ckpt_file(self.ckpt_list[ckpt_index]), \
            num_to_split, ckpt_offset

    def _choose_module_key(self, sd):
        """(ref: state_dict_factory.py:161)"""
        if "module" in sd and "model" in sd:
            raise RuntimeError(
                "checkpoint has both 'model' and 'module' keys, not sure "
                "how to proceed")
        if "module" in sd:
            return "module"
        if "model" in sd:
            return "model"
        raise RuntimeError("checkpoint contains neither 'model' nor 'module'")

    def get_module(self, sd):
        if self.module_key is None:
            return sd
        if self.module_key == AUTO_MODULE_KEY:
            return sd[self._choose_module_key(sd)]
        return sd[self.module_key]

    def set_module(self, sd, module):
        if self.module_key is None:
            sd = module
        elif self.module_key == AUTO_MODULE_KEY:
            sd[self._choose_module_key(sd)] = module
        else:
            sd[self.module_key] = module
        return sd

    def check_ckpt_list(self):
        assert len(self.ckpt_list) > 0
        # all files must exist (ref: :188 sanity check via first file)
        for p in self.ckpt_list:
            assert os.path.exists(p), f"checkpoint file {p} does not exist"

    @abstractmethod
    def merge_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def split_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def sanity_check(self, ckpt_file_name):
        ...


class MegatronSDLoader(SDLoaderBase):
    """(ref: state_dict_factory.py:195) layout rules:
    merge/split axis 0: word_embeddings, mlp.dense_h_to_4h.{weight,bias},
    qkv (format-aware); axis 1: attention.dense.weight,
    mlp.dense_4h_to_h.weight; replicated: everything else."""

    def merge_query_key_value(self, param_list, ckpt_ver):
        """Three historical QKV layouts (ref: :225): v0 [(3*np*hn), h]
        needs interleaved regrouping; v1.0/v2.0 concatenate directly."""
        if ckpt_ver == 0:
            assert param_list[0].shape[0] % 3 == 0
            size_qkv = param_list[0].shape[0] // 3
            split_tensors = [
                [p[i * size_qkv:(i + 1) * size_qkv] for i in range(3)]
                for p in param_list
            ]
            tensors = []
            for i in range(3):
                tensors.append(np.concatenate(
                    [t[i] for t in split_tensors], axis=0))
            return np.concatenate(tensors, axis=0)
        if ckpt_ver in (1.0, 2.0):
            return np.concatenate(param_list, axis=0)
        raise AssertionError(
            f"checkpoint version: {ckpt_ver} is not supported")

    def split_query_key_value(self, param, num_to_split, offset, ckpt_ver):
        """(ref: :263)"""
        if ckpt_ver == 0:
            assert param.shape[0] % 3 == 0
            size_qkv = param.shape[0] // 3
            split_tensors = [param[i * size_qkv:(i + 1) * size_qkv]
                             for i in range(3)]
            assert split_tensors[0].shape[0] % num_to_split == 0
            split_size = split_tensors[0].shape[0] // num_to_split
            tensors = [t[offset * split_size:(offset + 1) * split_size]
                       for t in split_tensors]
            return np.concatenate(tensors, axis=0)
        if ckpt_ver in (1.0, 2.0):
            assert param.shape[0] % num_to_split == 0
            size_qkv = param.shape[0] // num_to_split
            return param[offset * size_qkv:(offset + 1) * size_qkv]
        raise AssertionError(
            f"checkpoint version: {ckpt_ver} is not supported")

    def get_checkpoint_version(self, state_dict) -> float:
        # ref: :414 — explicit self.version wins over the sd field
        if self.version is not None:
            return self.version
        return state_dict.get("checkpoint_version", 0)

    def merge_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        """(ref: :305)"""
        self.sanity_check(self.ckpt_list[0])
        sd_list = self.get_merge_state_dicts(mp_world_size, mp_rank)
        ds_sd = copy.deepcopy(sd_list[0])
        new_client_sd = collections.OrderedDict()
        client_sd_list = [self.get_module(sd) for sd in sd_list]
        keys = client_sd_list[0].keys()
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = WeightQuantization(
            mlp_extra_grouping=mlp_extra_grouping,
            mp_size=mp_world_size) if quantize else None

        import jax.numpy as jnp
        for key in keys:
            value_list = [np.asarray(sd[key]) for sd in client_sd_list]
            if "attention.dense.weight" in key or \
                    "mlp.dense_4h_to_h.weight" in key:
                if quantize:
                    value_list = [np.asarray(v) for v in quantizer.Quantize(
                        [jnp.asarray(v) for v in value_list],
                        quantize_bits, groups, key=key)]
                new_client_sd[key] = np.concatenate(value_list, axis=1)
            elif "attention.query_key_value" in key:
                if quantize and "weight" in key:
                    value_list = [np.asarray(v) for v in quantizer.Quantize(
                        [jnp.asarray(v) for v in value_list],
                        quantize_bits, groups, key=key)]
                new_client_sd[key] = self.merge_query_key_value(
                    value_list, ckpt_ver)
            elif "mlp.dense_h_to_4h" in key or "word_embeddings.weight" in key:
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value_list = [np.asarray(v) for v in quantizer.Quantize(
                        [jnp.asarray(v) for v in value_list],
                        quantize_bits, groups, key=key)]
                new_client_sd[key] = np.concatenate(value_list, axis=0)
            else:
                new_client_sd[key] = value_list[0]
        all_scales = np.asarray(quantizer.merge_scales()) if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales, len(client_sd_list)

    def split_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        """(ref: :355)"""
        self.sanity_check(self.ckpt_list[0])
        sd, num_to_split, ckpt_offset = self.get_split_state_dict(
            mp_world_size, mp_rank)
        ds_sd = copy.deepcopy(sd)
        new_client_sd = collections.OrderedDict()
        client_sd = self.get_module(sd)
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = WeightQuantization(
            mlp_extra_grouping=mlp_extra_grouping,
            mp_size=mp_world_size) if quantize else None

        import jax.numpy as jnp
        for key in client_sd.keys():
            value = np.asarray(client_sd[key])
            if "attention.dense.weight" in key or \
                    "mlp.dense_4h_to_h.weight" in key:
                assert value.shape[1] % num_to_split == 0
                split_size = value.shape[1] // num_to_split
                if quantize:
                    [q] = quantizer.Quantize([jnp.asarray(value)],
                                             quantize_bits, groups, key=key)
                    value = np.asarray(q)
                new_client_sd[key] = value[
                    :, ckpt_offset * split_size:(ckpt_offset + 1) * split_size]
            elif "attention.query_key_value" in key:
                if quantize and "weight" in key:
                    [q] = quantizer.Quantize([jnp.asarray(value)],
                                             quantize_bits, groups, key=key)
                    value = np.asarray(q)
                new_client_sd[key] = self.split_query_key_value(
                    value, num_to_split, ckpt_offset, ckpt_ver)
            elif "mlp.dense_h_to_4h" in key or \
                    "word_embeddings.weight" in key:
                assert value.shape[0] % num_to_split == 0
                split_size = value.shape[0] // num_to_split
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    [q] = quantizer.Quantize([jnp.asarray(value)],
                                             quantize_bits, groups, key=key)
                    value = np.asarray(q)
                new_client_sd[key] = value[
                    ckpt_offset * split_size:(ckpt_offset + 1) * split_size]
            else:
                new_client_sd[key] = value
        all_scales = np.asarray(quantizer.merge_scales_split(num_to_split)
                                [ckpt_offset]) if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales

    def sanity_check(self, ckpt_file_name):
        keys_to_check = [
            "attention.dense.weight", "mlp.dense_4h_to_h.weight",
            "attention.query_key_value", "mlp.dense_h_to_4h.weight",
            "mlp.dense_h_to_4h.bias",
        ]
        sd = _load_ckpt_file(ckpt_file_name)
        module = self.get_module(sd) if self.module_key else sd

        def check_key_exist(partial_key, mod):
            return any(partial_key in k for k in mod.keys())

        for key in keys_to_check:
            assert check_key_exist(partial_key=key, mod=module), \
                f"key: {key} is not found in the checkpoint {ckpt_file_name}"
