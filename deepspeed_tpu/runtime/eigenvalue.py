"""Block-Hessian dominant-eigenvalue estimation (MoQ sensitivity signal).

Capability match for the reference's ``Eigenvalue``
(ref: deepspeed/runtime/eigenvalue.py:7): power iteration on each
transformer layer's Hessian; the dominant eigenvalue (normalized to
[0,1] across layers) slows the MoQ precision schedule for sensitive
layers.

TPU-native design: the reference does reverse-over-reverse autograd on
retained graphs (torch.autograd.grad(grads, params, grad_outputs=v)).
Here the Hessian-vector product is forward-over-reverse —
``jax.jvp(jax.grad(loss), (params,), (v,))`` — which XLA compiles into
one fused program, re-used across all power iterations and all blocks
(the block only changes the tangent's support, not the program).

Blocks: models with stacked per-layer weights (leading layer axis, as
our scan-based GPT) declare a ``layer_name`` pytree prefix; block ``i``
is the slice ``leaf[i]`` of every stacked leaf under that prefix.
"""

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.tree import tree_path_str as _path_str


class Eigenvalue:
    def __init__(self,
                 verbose: bool = False,
                 max_iter: int = 100,
                 tol: float = 1e-2,
                 stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "blocks",
                 layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        assert len(layer_name) > 0 and layer_num > 0
        self._hvp = None
        log_dist(
            f"eigenvalue enabled: max_iter={max_iter}, tol={tol}, "
            f"layer_name={layer_name}, layer_num={layer_num}", ranks=[0])

    # -- helpers -------------------------------------------------------

    def _is_block_leaf(self, path, leaf) -> bool:
        return (self.layer_name in _path_str(path)
                and hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] == self.layer_num)

    def _block_tangent(self, params, v_block, i):
        """Zero tangent tree with block ``i`` of stacked leaves set to v."""
        idx = [0]

        def visit(path, leaf):
            if self._is_block_leaf(path, leaf):
                z = jnp.zeros_like(leaf)
                z = z.at[i].set(v_block[idx[0]])
                idx[0] += 1
                return z
            return jnp.zeros_like(leaf)

        return jax.tree_util.tree_map_with_path(visit, params)

    def _extract_block(self, tree, i):
        out = []

        def visit(path, leaf):
            if self._is_block_leaf(path, leaf):
                out.append(leaf[i])
            return leaf

        jax.tree_util.tree_map_with_path(visit, tree)
        return out

    @staticmethod
    def _inner(xs, ys):
        return sum(jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32))
                   for x, y in zip(xs, ys))

    def _normalize(self, vs):
        norm = jnp.sqrt(self._inner(vs, vs)) + self.stability
        return [jnp.nan_to_num(v / norm, posinf=0.0, neginf=0.0) for v in vs]

    # -- main ----------------------------------------------------------

    def compute_eigenvalue(self,
                           loss_fn: Callable,
                           params,
                           batch,
                           rng: jax.Array,
                           scale: float = 1.0) -> Dict[str, Tuple[float, int]]:
        """Power iteration per block (ref: eigenvalue.py:61
        compute_eigenvalue). ``loss_fn(params, batch, rng) -> loss``.

        Returns {``<path>.<i>``: (normalized eigenvalue, layer_id)} keyed
        the way runtime/quantize.py's block_eigenvalue expects.

        The jitted HVP is cached on the instance: pass the *same*
        ``loss_fn`` object across calls to reuse the compiled program
        (batch/rng are traced arguments, so refreshes don't retrace).
        """
        if self._hvp is None or self._hvp[0] is not loss_fn:
            def grad_fn(p, b, r):
                return jax.grad(lambda q: jnp.asarray(
                    loss_fn(q, b, r), jnp.float32))(p)

            @jax.jit
            def hvp_fn(p, tangent, b, r):
                return jax.jvp(lambda q: grad_fn(q, b, r), (p,), (tangent,))[1]

            self._hvp = (loss_fn, hvp_fn)
        _, hvp_cached = self._hvp

        def hvp(p, tangent):
            return hvp_cached(p, tangent, batch, rng)

        key = jax.random.PRNGKey(0)  # fixed seed, as the reference
        # saves/restores torch rng state (eigenvalue.py:70-82)
        block_eigenvalue = []
        block_paths = []

        def collect(path, leaf):
            if self._is_block_leaf(path, leaf):
                block_paths.append(_path_str(path))
            return leaf

        jax.tree_util.tree_map_with_path(collect, params)
        if not block_paths:
            log_dist("model has no stacked block leaves; eigenvalue "
                     "computation skipped.", ranks=[0])
            return {}

        template = self._extract_block(params, 0)
        for i in range(self.layer_num):
            key, sub = jax.random.split(key)
            v = [jax.random.normal(k, t.shape, jnp.float32)
                 for k, t in zip(jax.random.split(sub, len(template)), template)]
            v = self._normalize(v)

            ev_cur, ev_prev, it = 1.0, 0.0, 0
            while (it < self.max_iter and abs(ev_cur) > 0
                   and abs((ev_cur - ev_prev) / ev_cur) >= self.tol):
                ev_prev = ev_cur
                tangent = self._block_tangent(params, v, i)
                hv = self._extract_block(hvp(params, tangent), i)
                hv = [jnp.nan_to_num(h.astype(jnp.float32),
                                     posinf=0.0, neginf=0.0) for h in hv]
                # intentional per-iteration host sync: the Rayleigh
                # quotient IS the while-loop's convergence predicate, so
                # the value must land on host before the next iteration
                # can be scheduled (audited for dslint DS001 — power
                # iteration is data-dependent, no batched pull possible)
                ev_cur = float(self._inner(hv, v))  # dslint: disable=DS001
                v = self._normalize(hv)
                v = [x / scale for x in v]
                it += 1

            ev_cur *= scale
            block_eigenvalue.append(ev_cur)
            if self.verbose:
                log_dist(f"block {i}: iters={it} eigenvalue={ev_cur}",
                         ranks=[0])

        block_eigenvalue = self.post_process(block_eigenvalue)
        ev_dict: Dict[str, Tuple[float, int]] = {}
        for i, value in enumerate(block_eigenvalue):
            for path in block_paths:
                ev_dict[f"{path}.{i}"] = (value, i)
        return ev_dict

    def post_process(self, values):
        """Map |eigenvalues| to [0,1]; invalid (0) blocks get 1.0 —
        maximum caution (ref: eigenvalue.py:152)."""
        if not values:
            return values
        max_value = abs(max(values, key=abs))
        if max_value == 0.0:
            return [1.0] * len(values)
        return [abs(v) / max_value if v != 0.0 else 1.0 for v in values]
