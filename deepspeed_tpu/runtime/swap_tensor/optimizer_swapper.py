"""NVMe swapping of optimizer state (ZeRO-Infinity tier).

TPU-native analog of the reference's optimizer swappers
(ref: deepspeed/runtime/swap_tensor/optimizer_utils.py:118 OptimizerSwapper,
 partitioned_optimizer_swapper.py:27 PartitionedOptimizerSwapper,
 pipelined_optimizer_swapper.py:60 PipelinedOptimizerSwapper): fp32
optimizer state lives in files on NVMe, grouped per parameter partition;
the step loop swaps a subgroup in, updates it on host cores, and swaps it
back out. The pipelined variant double-buffers — subgroup ``i+1`` reads
while ``i`` computes, and ``i-1`` writes behind (ref's
`SWAP_IN_GRADIENT/SWAP_OUT_PARAM` op overlap).
"""

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AlignedBuffer, AsyncIOHandle


class _KeyInfo:
    __slots__ = ("numel", "n_tensors", "on_disk")

    def __init__(self, numel: int, n_tensors: int):
        self.numel = numel
        self.n_tensors = n_tensors
        self.on_disk = False


class OptimizerStateSwapper:
    """Synchronous swapper: each key owns one file holding ``n_tensors``
    equal-length fp32 vectors laid out back to back."""

    def __init__(self, swap_dir: str, aio_handle: Optional[AsyncIOHandle] = None,
                 n_tensors: int = 2):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = aio_handle or AsyncIOHandle()
        self.n_tensors = n_tensors
        self._info: Dict[str, _KeyInfo] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_").replace(".", "_")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    def register(self, key: str, numel: int):
        self._info[key] = _KeyInfo(numel, self.n_tensors)

    def has_state(self, key: str) -> bool:
        info = self._info.get(key)
        return bool(info and info.on_disk)

    def swap_out(self, key: str, tensors: Sequence[np.ndarray]):
        info = self._info.get(key)
        if info is None:
            self.register(key, tensors[0].size)
            info = self._info[key]
        assert len(tensors) == info.n_tensors
        flat = np.concatenate([np.ascontiguousarray(t, np.float32).ravel()
                               for t in tensors])
        self.aio.sync_pwrite(flat, self._path(key))
        info.on_disk = True

    def swap_in(self, key: str) -> List[np.ndarray]:
        info = self._info[key]
        assert info.on_disk, f"no swapped state for {key}"
        flat = np.empty(info.numel * info.n_tensors, np.float32)
        self.aio.sync_pread(flat, self._path(key))
        return [flat[i * info.numel:(i + 1) * info.numel].copy()
                for i in range(info.n_tensors)]

    def purge(self):
        for key, info in self._info.items():
            p = self._path(key)
            if info.on_disk and os.path.exists(p):
                os.unlink(p)
            info.on_disk = False


class PipelinedOptimizerSwapper(OptimizerStateSwapper):
    """Double-buffered swapper: ``prefetch(next_key)`` starts the read for
    the next subgroup; ``swap_in`` returns instantly when the prefetch
    already landed. Writes go out asynchronously and are fenced at the next
    ``swap_out``/``finish`` (ref: pipelined_optimizer_swapper.py:60)."""

    def __init__(self, swap_dir: str, aio_handle: Optional[AsyncIOHandle] = None,
                 n_tensors: int = 2):
        super().__init__(swap_dir, aio_handle, n_tensors)
        self._prefetch_key: Optional[str] = None
        self._prefetch_buf: Optional[np.ndarray] = None
        self._write_pending = False
        self._outstanding: List[np.ndarray] = []

    def _fence(self):
        if self._write_pending or self._prefetch_key is not None:
            self.aio.wait()
            self._write_pending = False
            self._outstanding.clear()

    def prefetch(self, key: str):
        if key not in self._info or not self._info[key].on_disk:
            return
        self._fence()
        info = self._info[key]
        self._prefetch_buf = np.empty(info.numel * info.n_tensors, np.float32)
        self.aio.async_pread(self._prefetch_buf, self._path(key))
        self._prefetch_key = key

    def swap_in(self, key: str) -> List[np.ndarray]:
        if self._prefetch_key == key:
            self.aio.wait()  # land the prefetch
            info = self._info[key]
            flat = self._prefetch_buf
            self._prefetch_key = None
            self._prefetch_buf = None
            return [flat[i * info.numel:(i + 1) * info.numel]
                    for i in range(info.n_tensors)]
        self._fence()
        return super().swap_in(key)

    def swap_out_async(self, key: str, tensors: Sequence[np.ndarray]):
        info = self._info.get(key)
        if info is None:
            self.register(key, tensors[0].size)
            info = self._info[key]
        flat = np.concatenate([np.ascontiguousarray(t, np.float32).ravel()
                               for t in tensors])
        # keep references until fenced so the buffers survive the writes
        self._outstanding.append(flat)
        self.aio.async_pwrite(flat, self._path(key))
        info.on_disk = True
        self._write_pending = True

    def finish(self):
        self._fence()
