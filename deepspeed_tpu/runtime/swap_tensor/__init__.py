from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
    OptimizerStateSwapper, PipelinedOptimizerSwapper)

__all__ = ["AsyncTensorSwapper", "OptimizerStateSwapper",
           "PipelinedOptimizerSwapper"]
