"""Fire-and-forget tensor writes with a recycled aligned-buffer pool.

TPU-native analog of the reference's ``AsyncTensorSwapper``
(ref: deepspeed/runtime/swap_tensor/async_swapper.py:16): tensors are
copied into page-aligned host buffers and written to NVMe by the native
thread pool while the caller proceeds; buffers recycle once their write
completes.
"""

from typing import Dict, List

import numpy as np

from deepspeed_tpu.ops.aio import AlignedBuffer, AsyncIOHandle


class AsyncTensorSwapper:
    def __init__(self, aio_handle: AsyncIOHandle, buffer_count: int = 4,
                 buffer_size: int = 1 << 24):
        self.aio = aio_handle
        self.buffer_size = buffer_size
        self._free: List[AlignedBuffer] = [
            AlignedBuffer(buffer_size, dtype=np.uint8)
            for _ in range(buffer_count)]
        self._busy: List[AlignedBuffer] = []
        self.swap_out_bytes = 0

    def _acquire(self, nbytes: int) -> AlignedBuffer:
        if nbytes > self.buffer_size:
            # oversized tensor: dedicated transient buffer
            return AlignedBuffer(nbytes, dtype=np.uint8)
        if not self._free:
            # all buffers in flight: drain (the reference blocks the same
            # way when its pool is exhausted)
            self.wait()
        return self._free.pop()

    def swap_out(self, array: np.ndarray, path: str, offset: int = 0):
        buf = self._acquire(array.nbytes)
        flat = buf.array[:array.nbytes]
        flat[:] = np.ascontiguousarray(array).view(np.uint8).ravel()
        self.aio.async_pwrite(flat, path, offset)
        self._busy.append(buf)
        self.swap_out_bytes += array.nbytes

    def wait(self):
        """Drain all in-flight writes and recycle their buffers."""
        self.aio.wait()
        for buf in self._busy:
            if buf.nbytes <= self.buffer_size:
                self._free.append(buf)
            else:
                buf.free()
        self._busy = []
