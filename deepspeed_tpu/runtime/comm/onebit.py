"""Communication-compressed optimizers: 1-bit Adam, 0/1 Adam, 1-bit LAMB.

Capability analogs of the reference family
(ref: deepspeed/runtime/fp16/onebit/adam.py:14 OnebitAdam,
onebit/zoadam.py ZeroOneAdam, onebit/lamb.py OnebitLamb). Algorithm
semantics preserved:

- **warmup** (`freeze_step` steps): exact Adam/LAMB, variance updated;
- **compression stage**: variance FROZEN; the momentum update is compressed
  to error-feedback 1-bit (sign * L1-scale) before being applied — exactly
  the quantity the reference allreduces in compressed form
  (adam.py:217 compressed_allreduce of the momentum);
- 0/1 Adam: adaptive variance-freeze point (`var_freeze_step`) plus an
  exponentially-spaced local-step schedule between synchronizations
  (ref zoadam.py `local_step_scaler`).

Implemented as optax-style GradientTransformations. The compression math
(deepspeed_tpu.parallel.compressed.compress) runs on the globally-reduced
gradient here; when the engine's ``comm_backend_name='dcn_compressed'``
mode is active the same compress/decompress pair runs around the wire
inside the data-axis shard_map, so convergence behavior and wire format
stay consistent.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.parallel.compressed import compress


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any           # momentum (the compressed quantity)
    nu: Any           # variance (frozen after freeze_step)
    error: Any        # compression error feedback


def _compress_tree(tree, error):
    """Error-feedback 1-bit compress each leaf; returns (compressed, new_err).

    compress() yields (packed_bits, scale, new_error); the applied value is
    corrected - new_error == sign(corrected) * scale."""
    def rebuild(x, e):
        _packed, _scale, new_err = compress(x, e)
        compressed = (x.astype(jnp.float32) + e) - new_err
        return compressed, new_err

    pairs = jax.tree_util.tree_map(rebuild, tree, error)
    comp = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                  is_leaf=lambda p: isinstance(p, tuple))
    errs = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                  is_leaf=lambda p: isinstance(p, tuple))
    return comp, errs


def onebit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100, config_params=None
                ) -> optax.GradientTransformation:
    """1-bit Adam (ref: onebit/adam.py:14)."""
    if config_params:
        freeze_step = config_params.get("freeze_step", freeze_step)
        b1, b2 = config_params.get("betas", (b1, b2))
        eps = config_params.get("eps", eps)
        weight_decay = config_params.get("weight_decay", weight_decay)

    def init_fn(params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OnebitAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(z, params),
            nu=jax.tree_util.tree_map(z, params),
            error=jax.tree_util.tree_map(z, params))

    def update_fn(updates, state, params=None):
        count = state.count + 1
        in_warmup = count <= freeze_step

        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        # variance frozen after freeze_step
        nu = jax.tree_util.tree_map(
            lambda g, v: jnp.where(in_warmup,
                                   b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                   v),
            updates, state.nu)

        # compression stage: momentum passes through error-feedback 1-bit
        comp_mu, new_error = _compress_tree(mu, state.error)
        eff_mu = jax.tree_util.tree_map(
            lambda m, cm: jnp.where(in_warmup, m, cm), mu, comp_mu)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(in_warmup, e, ne), state.error, new_error)

        c = count.astype(jnp.float32)
        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def step(m, v, p):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0 and p is not None:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return -lr * upd

        if params is not None:
            new_updates = jax.tree_util.tree_map(step, eff_mu, nu, params)
        else:
            new_updates = jax.tree_util.tree_map(
                lambda m, v: step(m, v, None), eff_mu, nu)
        return new_updates, OnebitAdamState(count=count, mu=mu, nu=nu,
                                            error=error)

    return optax.GradientTransformation(init_fn, update_fn)


def zero_one_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100, var_update_scaler: int = 16,
                  local_step_scaler: int = 32678, local_step_clipper: int = 16,
                  config_params=None) -> optax.GradientTransformation:
    """0/1 Adam (ref: onebit/zoadam.py): variance updates on an
    exponentially-sparsifying schedule until var_freeze_step, then frozen;
    compression active throughout."""
    if config_params:
        var_freeze_step = config_params.get("var_freeze_step", var_freeze_step)
        var_update_scaler = config_params.get("var_update_scaler", var_update_scaler)
        b1, b2 = config_params.get("betas", (b1, b2))
        eps = config_params.get("eps", eps)
        weight_decay = config_params.get("weight_decay", weight_decay)

    def init_fn(params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OnebitAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(z, params),
            nu=jax.tree_util.tree_map(z, params),
            error=jax.tree_util.tree_map(z, params))

    def update_fn(updates, state, params=None):
        count = state.count + 1
        c = count.astype(jnp.float32)
        # variance update gate: every 2^(k) steps (k grows with count/scaler),
        # frozen entirely after var_freeze_step
        k = jnp.floor(c / var_update_scaler)
        interval = jnp.minimum(2.0 ** k, float(2 ** local_step_clipper))
        update_var = jnp.logical_and(
            count <= var_freeze_step,
            jnp.mod(c, interval) < 1.0)

        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: jnp.where(update_var,
                                   b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                   v),
            updates, state.nu)
        comp_mu, error = _compress_tree(mu, state.error)

        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def step(m, v, p):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0 and p is not None:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return -lr * upd

        if params is not None:
            new_updates = jax.tree_util.tree_map(step, comp_mu, nu, params)
        else:
            new_updates = jax.tree_util.tree_map(
                lambda m, v: step(m, v, None), comp_mu, nu)
        return new_updates, OnebitAdamState(count=count, mu=mu, nu=nu,
                                            error=error)

    return optax.GradientTransformation(init_fn, update_fn)


def onebit_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-6, weight_decay: float = 0.0,
                freeze_step: int = 100, max_coeff: float = 10.0,
                min_coeff: float = 0.01, config_params=None
                ) -> optax.GradientTransformation:
    """1-bit LAMB (ref: onebit/lamb.py): LAMB during warmup; after
    freeze_step the momentum is 1-bit compressed and the per-tensor trust
    ratios are FROZEN at their last warmup values (the reference's frozen
    scaling factors)."""
    if config_params:
        freeze_step = config_params.get("freeze_step", freeze_step)
        b1, b2 = config_params.get("betas", (b1, b2))
        eps = config_params.get("eps", eps)
        weight_decay = config_params.get("weight_decay", weight_decay)
        max_coeff = config_params.get("max_coeff", max_coeff)
        min_coeff = config_params.get("min_coeff", min_coeff)

    class State(NamedTuple):
        count: jnp.ndarray
        mu: Any
        nu: Any
        error: Any
        frozen_ratio: Any   # last trust ratios from warmup

    def init_fn(params):
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        one = lambda p: jnp.ones([], jnp.float32)
        return State(count=jnp.zeros([], jnp.int32),
                     mu=jax.tree_util.tree_map(z, params),
                     nu=jax.tree_util.tree_map(z, params),
                     error=jax.tree_util.tree_map(z, params),
                     frozen_ratio=jax.tree_util.tree_map(one, params))

    def update_fn(updates, state, params):
        assert params is not None, "1-bit LAMB requires params"
        count = state.count + 1
        in_warmup = count <= freeze_step
        c = count.astype(jnp.float32)

        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: jnp.where(in_warmup,
                                   b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                   v),
            updates, state.nu)
        comp_mu, new_error = _compress_tree(mu, state.error)
        eff_mu = jax.tree_util.tree_map(
            lambda m, cm: jnp.where(in_warmup, m, cm), mu, comp_mu)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(in_warmup, e, ne), state.error, new_error)

        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def lamb_parts(m, v, p):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(upd)
            live_ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                                   jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                                   1.0)
            return upd, live_ratio

        parts = jax.tree_util.tree_map(lamb_parts, eff_mu, nu, params)
        upds = jax.tree_util.tree_map(lambda p: p[0], parts,
                                      is_leaf=lambda p: isinstance(p, tuple))
        live = jax.tree_util.tree_map(lambda p: p[1], parts,
                                      is_leaf=lambda p: isinstance(p, tuple))
        ratio = jax.tree_util.tree_map(
            lambda lv, fr: jnp.where(in_warmup, lv, fr), live,
            state.frozen_ratio)
        new_updates = jax.tree_util.tree_map(
            lambda u, r: -lr * r * u, upds, ratio)
        return new_updates, State(count=count, mu=mu, nu=nu, error=error,
                                  frozen_ratio=ratio)

    return optax.GradientTransformation(init_fn, update_fn)
