"""MoQ — Mixture-of-Quantization quantize-aware training.

Capability match for the reference's ``Quantizer``
(ref: deepspeed/runtime/quantize.py:12): the weights the forward pass
sees are re-quantized after each optimizer step at a bit-width that
anneals from ``quantize_bits_start`` down to ``quantize_bits_target``,
one bit per period, with the period doubling at each drop (and
optionally scaled by the layer's Hessian eigenvalue so sensitive layers
anneal slower).

TPU-native design. In fp16 mode the reference quantizes the bit16 model
copies while the optimizer's fp32 masters stay full precision
(ref: engine.py:1789-1800 quantizes optimizer.bit16_groups /
fp16_groups). Our engine materializes the compute-dtype copy *inside*
the jitted step (a cast of the fp32 masters), so quantization goes in
the same place: :meth:`make_transform` returns a pure function the
engine applies to the cast params inside ``jit`` — a straight-through
fake-quant whose bit-widths are static (trace-time) constants. Masters
are never quantized; a recompile happens only at the rare precision
switches. Host-side schedule bookkeeping lives in :meth:`advance`.

``quantize_tree`` keeps the reference's destructive fp32 behavior
(ref: engine.py:1797 quantizes optimizer.param_groups when fp16 is off)
for host-resident masters and for standalone use.

"Layers" are identified by pytree path; stacked-layer models (our GPT
keeps per-layer weights stacked on axis 0 for ``lax.scan``) get
per-layer bit-widths by slicing that axis.
"""

import math
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops import quantizer as qops
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import tree_path_str

# number of 2-dimensional parameters per transformer layer — the step
# counter advances by this per quantize() call (ref: quantize.py:9
# TWO_D_PARAMS = 6)
TWO_D_PARAMS = 6


class Quantizer:
    """MoQ schedule driver (ref: deepspeed/runtime/quantize.py:12).

    Parameters mirror the reference ctor; ``layer_num > 0`` enables the
    per-layer bit schedule (with ``stacked_prefix`` naming the pytree
    subtree whose leaves carry a leading layer axis — plumbed from the
    eigenvalue ``layer_name`` config).
    """

    def __init__(self,
                 q_target_bits: int = 8,
                 q_start_bits: int = 16,
                 q_period: int = 100,
                 q_offset: int = 100,
                 q_groups: int = 1,
                 q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.01,
                 q_type: str = "symmetric",
                 q_rounding: str = "nearest",
                 q_verbose: bool = False,
                 q_eigenvalue: bool = False,
                 layer_num: int = 0,
                 stacked_prefix: str = "blocks"):
        self.q_target_bits = q_target_bits
        n = layer_num if layer_num != 0 else 1
        self.q_start_bits = [q_start_bits] * n
        self.q_period = [q_period] * n
        self.q_offset = q_offset
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.layer_num = layer_num
        self.stacked_prefix = stacked_prefix
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        self._sr_key = jax.random.PRNGKey(17)

    @classmethod
    def from_config(cls, qcfg, layer_num: int = 0) -> "Quantizer":
        """Build from a QuantizeTrainingConfig (runtime/config.py).
        ``eigenvalue.layer_name`` doubles as the stacked-subtree prefix
        so the Quantizer and Eigenvalue agree on what a "layer" is."""
        return cls(
            q_target_bits=qcfg.quantize_bits_target,
            q_start_bits=qcfg.quantize_bits_start,
            q_period=qcfg.quantize_period,
            q_offset=qcfg.quantize_schedule_offset,
            q_groups=qcfg.quantize_groups,
            q_mixed_fp16=qcfg.fp16_mixed_quantize,
            q_change_ratio=qcfg.quantize_change_ratio,
            q_type=qcfg.quantize_type,
            q_rounding=qcfg.rounding,
            q_verbose=qcfg.quantize_verbose,
            q_eigenvalue=qcfg.eigenvalue.enabled,
            layer_num=layer_num or qcfg.eigenvalue.layer_num,
            stacked_prefix=qcfg.eigenvalue.layer_name)

    # -- schedule (host side) -----------------------------------------

    @property
    def active(self) -> bool:
        """Quantization in effect (warmup offset has elapsed)."""
        return self.q_offset == 0

    def any_precision_switch(self) -> bool:
        """Will some layer change precision within the next step?
        (ref: quantize.py:46)"""
        if self.layer_num == 0:
            return True
        stride = TWO_D_PARAMS * self.layer_num
        return any(
            self.q_start_bits[i] != self.q_target_bits
            and self.qsteps + stride >= self.q_period[i]
            for i in range(self.layer_num))

    def _advance_layer(self, index: int, factor: int) -> bool:
        """Bit-width annealing for one layer slot (ref: quantize.py:131-157
        compute_quantization schedule half). Returns True on a switch."""
        switched = False
        if self.q_start_bits[index] != self.q_target_bits and \
                self.qsteps >= self.q_period[index]:
            self.quantize_real_ratio = 1.0
            switched = True
            if self.q_eigenvalue:
                self.q_period[index] <<= 1
                self.q_period[index] *= factor
                self.q_start_bits[index] -= 1
            else:
                for i in range(len(self.q_start_bits)):
                    self.q_start_bits[i] -= 1
                    self.q_period[i] <<= 1
            if self.q_verbose:
                logger.info(
                    f"MoQ: bits={self.q_start_bits[index]} step={self.qsteps} "
                    f"period={self.q_period[index]} layer={index}")
        assert self.q_start_bits[index] >= self.q_target_bits, \
            "Quantization bit is lower than target precision bits!"
        return switched

    def advance(self,
                overflow: bool = False,
                eigenvalue_enabled: bool = False,
                block_eigenvalue: Optional[Dict[str, Tuple[float, int]]] = None
                ) -> bool:
        """Advance the schedule one optimizer step; returns True when a
        bit-width changed (the engine then rebuilds its jitted step)."""
        if overflow and not eigenvalue_enabled:
            return False
        self.step()
        self.update_fp16_ratio()
        if self.q_offset > 0:
            if self.qsteps >= self.q_offset:
                self.q_offset = 0
                self.qsteps = 0
                return True  # quantization turns on → rebuild
            return False
        block_eigenvalue = block_eigenvalue or {}
        switched = False
        if self.layer_num > 0 and block_eigenvalue:
            # per-layer factors from the eigenvalue map
            factors = {}
            for _, (ev, layer_id) in block_eigenvalue.items():
                factors[layer_id] = 1 + math.floor(ev * 4)
            for i in range(self.layer_num):
                switched |= self._advance_layer(i, factors.get(i, 1))
        else:
            switched |= self._advance_layer(0, 1)
        return switched

    def step(self):
        self.qsteps += TWO_D_PARAMS * (self.layer_num if self.layer_num else 1)

    def update_fp16_ratio(self):
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    # -- in-jit transform (the engine's compute-copy path) -------------

    def make_transform(self, step_at_build: Optional[int] = None) -> Callable:
        """Freeze the current bit-widths into a pure function
        ``f(params, rng, step=None) -> params`` applied to the compute-dtype
        copy inside the jitted train step. Straight-through gradients; fp32
        masters untouched. The engine rebuilds (recompiles) whenever
        :meth:`advance` reports a switch.

        Bit-widths are trace-time constants (they change only at the rare
        precision switches, which recompile anyway). The fp16 mixing ratio
        decays *every* step (ref: quantize.py:44 update_fp16_ratio applied
        each compute_quantization call), so it is reconstructed in-jit from
        the traced ``step``: ratio(step) = max(0, ratio_at_build -
        change_ratio * (step - step_at_build)). Pass ``step_at_build`` =
        the engine's applied-step count at (re)build time."""
        bits = tuple(self.q_start_bits)
        groups = self.q_groups
        symmetric = self.q_type == "symmetric"
        stochastic = self.q_rounding == "stochastic"
        layer_num = self.layer_num
        prefix = self.stacked_prefix
        ratio0 = self.quantize_real_ratio if self.q_mixed_fp16 else 0.0
        change = self.q_change_ratio if self.q_mixed_fp16 else 0.0
        step0 = step_at_build
        near_target = self.q_start_bits[0] >= (self.q_target_bits - 1)

        def fq(x, b, key, ratio):
            q = qops.quantize_dequantize(
                x, groups=groups, bits=b, symmetric=symmetric,
                stochastic=stochastic, rng=key)
            if ratio is not None and near_target:
                r = ratio.astype(x.dtype)
                q = x * r + (1.0 - r) * q
            return x + jax.lax.stop_gradient(q - x)

        def transform(params, rng, step=None):
            keys = [rng]
            if ratio0 > 0.0 and step is not None and step0 is not None:
                ratio = jnp.maximum(
                    0.0, ratio0 - change *
                    (step.astype(jnp.float32) - float(step0)))
            elif ratio0 > 0.0:
                ratio = jnp.asarray(ratio0, jnp.float32)
            else:
                ratio = None

            def visit(path, leaf):
                if leaf.ndim <= 1:
                    return leaf
                keys[0], sub = jax.random.split(keys[0])
                name = tree_path_str(path)
                if (layer_num > 0 and prefix in name and leaf.ndim >= 3
                        and leaf.shape[0] == layer_num):
                    slices = [
                        fq(leaf[i], bits[i], jax.random.fold_in(sub, i),
                           ratio)
                        for i in range(layer_num)
                    ]
                    return jnp.stack(slices)
                return fq(leaf, bits[0], sub, ratio)

            return jax.tree_util.tree_map_with_path(visit, params)

        return transform

    # -- host-side destructive application -----------------------------

    def quantize_tree(self,
                      params,
                      overflow: bool = False,
                      eigenvalue_enabled: bool = False,
                      block_eigenvalue: Optional[Dict[str, Tuple[float, int]]] = None):
        """Advance the schedule AND quantize ``params`` in one shot,
        returning a new tree. This is the reference's fp32-mode behavior
        (ref: engine.py:1797 — with no separate master copy the one
        parameter set is quantized in place); the engine's fp16/bf16
        path uses :meth:`make_transform` instead."""
        if overflow and not eigenvalue_enabled:
            return params
        self.advance(overflow=overflow,
                     eigenvalue_enabled=eigenvalue_enabled,
                     block_eigenvalue=block_eigenvalue)
        if not self.active:
            return params
        self._sr_key, sub = jax.random.split(self._sr_key)
        return self.make_transform()(params, sub)
