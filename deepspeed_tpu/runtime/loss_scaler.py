"""Static and dynamic loss scaling, jit-compatible.

TPU-native equivalent of the reference scalers
(ref: deepspeed/runtime/fp16/loss_scaler.py:56 LossScaler, :79
DynamicLossScaler). The reference mutates Python state per step; here the
scaler state is a small pytree threaded through the jitted train step so
overflow detection + scale adjustment + step-skip all compile into the one
XLA program (no host sync on the hot path).
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    loss_scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray          # i32: consecutive non-overflow steps
    hysteresis: jnp.ndarray          # i32: remaining tolerated overflows
    overflow: jnp.ndarray            # bool: last step overflowed


def init_state(static_scale: float = 0.0,
               initial_scale_power: int = 16,
               hysteresis: int = 2) -> LossScaleState:
    scale = static_scale if static_scale > 0 else 2.0 ** initial_scale_power
    return LossScaleState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
        overflow=jnp.asarray(False, jnp.bool_),
    )


def has_overflow(grads: Any) -> jnp.ndarray:
    """Global inf/nan check over a grad pytree (ref: loss_scaler.py:29
    CheckOverflow / stage_1_and_2.py:1799 has_overflow_serial). On TPU this
    is a single fused reduction, no host round-trip."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32)))) for l in leaves]
    return jnp.any(jnp.stack(flags))


def update(state: LossScaleState, overflow: jnp.ndarray, *,
           dynamic: bool, scale_window: int = 1000, scale_factor: float = 2.0,
           min_scale: float = 1.0, max_hysteresis: int = 2) -> LossScaleState:
    """Post-step scale adjustment (ref: DynamicLossScaler.update_scale
    loss_scaler.py:130). Pure function of (state, overflow)."""
    if not dynamic:
        return state._replace(overflow=overflow,
                              good_steps=state.good_steps + 1)

    def on_overflow(s: LossScaleState) -> LossScaleState:
        hys = s.hysteresis - 1
        new_scale = jnp.where(
            hys <= 0,
            jnp.maximum(s.loss_scale / scale_factor, min_scale),
            s.loss_scale)
        return LossScaleState(loss_scale=new_scale,
                              good_steps=jnp.asarray(0, jnp.int32),
                              hysteresis=jnp.maximum(hys, 0),
                              overflow=jnp.asarray(True, jnp.bool_))

    def on_good(s: LossScaleState) -> LossScaleState:
        good = s.good_steps + 1
        grow = good % scale_window == 0
        new_scale = jnp.where(grow, s.loss_scale * scale_factor, s.loss_scale)
        return LossScaleState(loss_scale=new_scale,
                              good_steps=good,
                              hysteresis=jnp.asarray(max_hysteresis, jnp.int32),
                              overflow=jnp.asarray(False, jnp.bool_))

    return jax.lax.cond(overflow, on_overflow, on_good, state)


def scale_loss(loss: jnp.ndarray, state: LossScaleState) -> jnp.ndarray:
    return loss * state.loss_scale.astype(loss.dtype)


def unscale_grads(grads: Any, state: LossScaleState) -> Any:
    inv = 1.0 / state.loss_scale
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * inv), grads)
