"""Compressed row-sparse tensor + sparse gradient allreduce.

Capability match for the reference's ``SparseTensor``
(ref: deepspeed/runtime/sparse_tensor.py:11) and the engine's sparse
embedding-gradient allreduce (ref: runtime/engine.py:2178-2250
sparse_allreduce_bucket: allgather indices+values, sum densely).

TPU context: jax/XLA gradients are dense, so the sparse path is an
*opt-in* bandwidth optimization for embedding-style grads whose rows
are mostly zero — worthwhile over DCN where bytes are precious, not
over ICI. Static shapes rule: ``from_dense`` takes ``max_rows`` (the
row-count capacity, a trace-time constant) and pads, exactly how the
reference's variable-length allgather becomes a fixed-size program.
"""

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class SparseTensor:
    """Row-compressed 2-D tensor: ``indices`` (n,), ``values`` (n, cols),
    ``dense_size`` (rows, cols). Row capacity is static; unused slots
    hold index ``rows`` (one-past-end sentinel) and zero values so that
    ``to_dense`` scatter-adds are a no-op for them."""

    def __init__(self, indices: jnp.ndarray, values: jnp.ndarray,
                 dense_size: Tuple[int, int]):
        self.indices = indices
        self.values = values
        self.dense_size = tuple(dense_size)

    @staticmethod
    def type() -> str:
        return "deepspeed_tpu.SparseTensor"

    @classmethod
    def from_dense(cls, dense: jnp.ndarray,
                   max_rows: Optional[int] = None) -> "SparseTensor":
        """Compress rows with any non-zero entry (ref:
        sparse_tensor.py:22 nonzero of row sums). ``max_rows`` bounds
        the static capacity (defaults to all rows — no compression win,
        but shape-safe)."""
        rows, _ = dense.shape
        max_rows = max_rows if max_rows is not None else rows
        row_mass = jnp.sum(jnp.abs(dense), axis=1)
        if not isinstance(dense, jax.core.Tracer):
            # concrete call: catch capacity overflow (silently dropping
            # rows would corrupt the gradient); inside jit the caller
            # must size max_rows to the worst case
            n_nonzero = int(jnp.sum(row_mass > 0))
            if n_nonzero > max_rows:
                raise ValueError(
                    f"{n_nonzero} nonzero rows exceed max_rows={max_rows}; "
                    "raise the capacity or gradients would be dropped")
        # top-k by mass: static-shape stand-in for nonzero(); rows with
        # zero mass land at the tail and are masked out
        _, idx = jax.lax.top_k(row_mass, max_rows)
        mask = row_mass[idx] > 0
        indices = jnp.where(mask, idx, rows)
        values = jnp.where(mask[:, None], dense[idx], 0.0)
        return cls(indices, values, dense.shape)

    def to_dense(self) -> jnp.ndarray:
        rows, cols = self.dense_size
        buf = jnp.zeros((rows + 1, cols), self.values.dtype)  # +1: sentinel row
        buf = buf.at[self.indices].add(self.values)
        return buf[:rows]

    def sparse_size(self) -> Tuple[int, int]:
        index_size = self.indices.shape[0]
        value_size = self.values.shape[0] * self.values.shape[1]
        dense_size = self.dense_size[0] * self.dense_size[1]
        return index_size + value_size, dense_size

    def add(self, b: "SparseTensor") -> None:
        assert self.dense_size == b.dense_size
        self.indices = jnp.concatenate([self.indices, b.indices])
        self.values = jnp.concatenate([self.values, b.values])

    def __str__(self):
        sparse_size, dense_size = self.sparse_size()
        return (f"deepspeed_tpu.SparseTensor(indices_size="
                f"{self.indices.shape}, values_size={self.values.shape}, "
                f"dense_size={self.dense_size}, "
                f"reduction_factor={dense_size / sparse_size:.2f})")

    __repr__ = __str__


def sparse_all_reduce(indices: jnp.ndarray, values: jnp.ndarray,
                      dense_size: Tuple[int, int],
                      axis_name: str) -> jnp.ndarray:
    """Allreduce of row-sparse grads inside ``shard_map``: allgather the
    (indices, values) pairs over ``axis_name`` and densify locally —
    the reference's sparse_allreduce_bucket recipe (ref:
    engine.py:2211-2236: all_gather of values+indices, caller sums) with
    XLA's ``all_gather`` riding ICI/DCN. Returns the summed DENSE grad
    (mean is the caller's division, as in the reference's
    ``average_sparse_gradients``)."""
    all_idx = jax.lax.all_gather(indices, axis_name)      # (world, n)
    all_val = jax.lax.all_gather(values, axis_name)       # (world, n, cols)
    rows, cols = dense_size
    buf = jnp.zeros((rows + 1, cols), values.dtype)
    buf = buf.at[all_idx.reshape(-1)].add(
        all_val.reshape(-1, cols))
    return buf[:rows]


def average_sparse(st_list: Sequence[SparseTensor],
                   world_size: int) -> List[SparseTensor]:
    """Scale values by 1/world (ref: engine.py:2191
    average_sparse_gradients)."""
    out = []
    for st in st_list:
        out.append(SparseTensor(st.indices, st.values / world_size,
                                st.dense_size))
    return out
