"""Learning-rate schedules.

TPU-native equivalents of the reference schedules
(ref: deepspeed/runtime/lr_schedules.py — LRRangeTest :310, OneCycle :417,
WarmupLR :706, WarmupDecayLR :802). Implemented as pure ``step -> lr``
functions (optax-style schedules) so they trace cleanly inside a jitted
train step; a thin stateful wrapper provides the reference's
``step()/get_lr()/state_dict()`` object API.
"""

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

# config keys (ref: lr_schedules.py:29-78)
LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
WARMUP_TYPE = "warmup_type"
WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"
TOTAL_NUM_STEPS = "total_num_steps"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

Schedule = Callable[[Any], Any]  # step -> lr


def lr_range_test(min_lr: float = 1e-3, step_rate: float = 1.0,
                  step_size: int = 2000, staircase: bool = False) -> Schedule:
    """LR range test: lr grows (continuously or staircase) with step
    (ref: lr_schedules.py:310 LRRangeTest)."""

    def schedule(step):
        interval = step / step_size
        if staircase:
            interval = jnp.floor(interval)
        return min_lr * (1.0 + interval * step_rate)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = WARMUP_LOG_RATE) -> Schedule:
    """Warmup then constant (ref: lr_schedules.py:706 WarmupLR)."""
    warmup_num_steps = max(2, warmup_num_steps)
    delta = warmup_max_lr - warmup_min_lr
    inverse_log_warm_up = 1.0 / math.log(warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == WARMUP_LOG_RATE:
            gamma = inverse_log_warm_up * jnp.log(jnp.maximum(step, 1.0) + 1.0)
        else:
            gamma = step / warmup_num_steps
        gamma = jnp.minimum(gamma, 1.0)
        return warmup_min_lr + delta * gamma

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = WARMUP_LOG_RATE) -> Schedule:
    """Warmup then linear decay to zero (ref: lr_schedules.py:802)."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_c = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base(step)
        decay = jnp.maximum(
            0.0, (total_num_steps - step) /
            jnp.maximum(1.0, float(total_num_steps - warmup_num_steps_c)))
        return jnp.where(step < warmup_num_steps_c, warm, warmup_max_lr * decay)

    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0) -> Schedule:
    """1-cycle policy: min->max over first leg, max->min over second, then
    decay (ref: lr_schedules.py:417 OneCycle)."""
    first = float(cycle_first_step_size)
    second = float(cycle_second_step_size
                   if cycle_second_step_size is not None else cycle_first_step_size)
    total_cycle = first + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)

        up_frac = jnp.clip(step / first, 0.0, 1.0)
        down_frac = jnp.clip((step - first) / second, 0.0, 1.0)
        in_decay = step > total_cycle

        lr_up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac
        lr_down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac
        lr_cycle = jnp.where(step <= first, lr_up, lr_down)

        if decay_step_size > 0 and decay_lr_rate > 0:
            decay_steps = jnp.floor((step - total_cycle) / decay_step_size)
            lr_decay = cycle_min_lr / (1.0 + decay_lr_rate * jnp.maximum(decay_steps, 0.0))
        else:
            lr_decay = jnp.full_like(lr_cycle, cycle_min_lr)
        return jnp.where(in_decay, lr_decay, lr_cycle)

    return schedule


def constant_lr(lr: float) -> Schedule:
    def schedule(step):
        del step
        return jnp.asarray(lr, jnp.float32)

    return schedule


def get_lr_schedule(name: Optional[str], params: Dict[str, Any],
                    base_lr: float = 1e-3) -> Schedule:
    """name+params (the JSON `scheduler` section) -> schedule fn
    (ref: deepspeed/runtime/lr_schedules.py add_tuning_arguments dispatch)."""
    if name is None:
        return constant_lr(base_lr)
    if name == LR_RANGE_TEST:
        return lr_range_test(
            min_lr=params.get(LR_RANGE_TEST_MIN_LR, 1e-3),
            step_rate=params.get(LR_RANGE_TEST_STEP_RATE, 1.0),
            step_size=params.get(LR_RANGE_TEST_STEP_SIZE, 2000),
            staircase=params.get(LR_RANGE_TEST_STAIRCASE, False))
    if name == WARMUP_LR:
        return warmup_lr(
            warmup_min_lr=params.get(WARMUP_MIN_LR, 0.0),
            warmup_max_lr=params.get(WARMUP_MAX_LR, base_lr),
            warmup_num_steps=params.get(WARMUP_NUM_STEPS, 1000),
            warmup_type=params.get(WARMUP_TYPE, WARMUP_LOG_RATE))
    if name == WARMUP_DECAY_LR:
        return warmup_decay_lr(
            total_num_steps=params[TOTAL_NUM_STEPS],
            warmup_min_lr=params.get(WARMUP_MIN_LR, 0.0),
            warmup_max_lr=params.get(WARMUP_MAX_LR, base_lr),
            warmup_num_steps=params.get(WARMUP_NUM_STEPS, 1000),
            warmup_type=params.get(WARMUP_TYPE, WARMUP_LOG_RATE))
    if name == ONE_CYCLE:
        return one_cycle(
            cycle_min_lr=params[CYCLE_MIN_LR],
            cycle_max_lr=params[CYCLE_MAX_LR],
            cycle_first_step_size=params.get(CYCLE_FIRST_STEP_SIZE, 2000),
            cycle_second_step_size=params.get(CYCLE_SECOND_STEP_SIZE),
            decay_step_size=params.get(DECAY_STEP_SIZE, 0),
            decay_lr_rate=params.get(DECAY_LR_RATE, 0.0))
    raise ValueError(f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")


class LRScheduler:
    """Stateful wrapper with the reference object API
    (step/get_lr/state_dict/load_state_dict)."""

    def __init__(self, schedule: Schedule, last_batch_iteration: int = -1):
        self.schedule = schedule
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self.schedule(max(0, self.last_batch_iteration)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
