"""Config helpers (ref: deepspeed/runtime/config_utils.py)."""

import json
from typing import Any, Dict


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys during JSON parsing."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


class ScientificNotationEncoder(json.JSONEncoder):
    """Print big numbers in scientific notation (ref config_utils.py)."""

    def iterencode(self, o, _one_shot=False):
        if isinstance(o, float) and o >= 1e3:
            return iter([f"{o:.1e}"])
        return super().iterencode(o, _one_shot=_one_shot)
