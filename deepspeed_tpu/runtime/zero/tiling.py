"""TiledLinear: huge linear layers computed tile-by-tile.

Capability match for the reference's ``TiledLinear``
(ref: deepspeed/runtime/zero/tiling.py:27): break a linear's input and
output dimensions into tiles so only one tile's weights/activations are
live at a time — there ZeRO-3 fetches/releases per tile; here the tiles
are a stacked array sharded over the ``fsdp`` axis and the per-tile
matmul is wrapped in ``jax.checkpoint`` so XLA frees tile activations
between steps of the ``lax.scan`` instead of keeping the full GEMM's
intermediates live.

Functional API (params are a pytree, not a module):

    params = tiled_linear_init(rng, in_features, out_features,
                               in_splits=4, out_splits=4)
    y = tiled_linear(x, params)

Tile layout: ``kernel`` has shape (out_splits, in_splits, in_tile,
out_tile); ``bias`` (when used) has shape (out_splits, out_tile).
Uneven splits are handled the reference's way — CSR-style partition
boundaries (ref: tiling.py:94 partition call) — except tiles here must
be equal-sized for stacking; ``in_features % in_splits == 0`` is
required (pad to a multiple, the idiomatic TPU answer anyway).
"""

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp


def tiled_linear_init(rng: jax.Array,
                      in_features: int,
                      out_features: int,
                      in_splits: int = 1,
                      out_splits: int = 1,
                      bias: bool = True,
                      dtype=jnp.float32,
                      init_scale: Optional[float] = None) -> Dict:
    if in_splits < 1 or in_splits > in_features:
        raise RuntimeError("in splits must be in range [1, in_features].")
    if out_splits < 1 or out_splits > out_features:
        raise RuntimeError("out splits must be in range [1, out_features].")
    if in_features % in_splits or out_features % out_splits:
        raise RuntimeError(
            "tile splits must divide features evenly on TPU (pad to a "
            f"multiple): {in_features}%{in_splits}, {out_features}%{out_splits}")
    in_tile = in_features // in_splits
    out_tile = out_features // out_splits
    scale = init_scale if init_scale is not None else 1.0 / (in_features ** 0.5)
    kernel = jax.random.normal(
        rng, (out_splits, in_splits, in_tile, out_tile), dtype) * scale
    params = {"kernel": kernel}
    if bias:
        params["bias"] = jnp.zeros((out_splits, out_tile), dtype)
    return params


def from_dense(kernel: jnp.ndarray, bias: Optional[jnp.ndarray],
               in_splits: int, out_splits: int) -> Dict:
    """Tile an existing dense (in, out) kernel (ref: tiling.py:150
    copy_params_from / init_linear)."""
    in_features, out_features = kernel.shape
    in_tile = in_features // in_splits
    out_tile = out_features // out_splits
    k = kernel.reshape(in_splits, in_tile, out_splits, out_tile)
    k = k.transpose(2, 0, 1, 3)  # (out_s, in_s, in_tile, out_tile)
    params = {"kernel": k}
    if bias is not None:
        params["bias"] = bias.reshape(out_splits, out_tile)
    return params


def to_dense(params: Dict):
    """Inverse of :func:`from_dense`."""
    k = params["kernel"]
    out_s, in_s, in_t, out_t = k.shape
    kernel = k.transpose(1, 2, 0, 3).reshape(in_s * in_t, out_s * out_t)
    bias = params.get("bias")
    if bias is not None:
        bias = bias.reshape(out_s * out_t)
    return kernel, bias


@partial(jax.jit, static_argnames=("combine_out_splits", "use_remat"))
def tiled_linear(x: jnp.ndarray,
                 params: Dict,
                 combine_out_splits: bool = True,
                 use_remat: bool = True):
    """y = x @ W + b computed per (out_tile, in_tile) pair
    (ref: tiling.py:122 forward's double loop). The in_splits reduction
    runs as a ``lax.scan`` so only one partial product is live; remat
    drops tile intermediates on the backward pass."""
    kernel = params["kernel"]
    out_s, in_s, in_t, out_t = kernel.shape
    bias = params.get("bias")

    x_tiles = x.reshape(x.shape[:-1] + (in_s, in_t))
    x_tiles = jnp.moveaxis(x_tiles, -2, 0)  # (in_s, ..., in_t)

    def one_out(kernel_o, bias_o):
        def body(acc, operand):
            xt, kt = operand
            if use_remat:
                part = jax.checkpoint(lambda a, b: a @ b)(xt, kt)
            else:
                part = xt @ kt
            return acc + part, None

        init = jnp.zeros(x.shape[:-1] + (out_t,), x.dtype)
        acc, _ = jax.lax.scan(body, init, (x_tiles, kernel_o))
        if bias_o is not None:
            acc = acc + bias_o
        return acc

    outs = jax.vmap(one_out, in_axes=(0, 0 if bias is not None else None),
                    out_axes=-2)(kernel, bias)
    # outs: (..., out_s, out_t)
    if combine_out_splits:
        return outs.reshape(x.shape[:-1] + (out_s * out_t,))
    return [outs[..., i, :] for i in range(out_s)]


def tiled_linear_partition_rules(prefix: str = ".*kernel"):
    """fsdp-shard the stacked tile axes: with (out_s, in_s, ...) leading,
    the fsdp axis splits whole tiles, the unit ZeRO-3 fetches/releases."""
    from deepspeed_tpu.parallel.sharding import PartitionRule
    from jax.sharding import PartitionSpec as P
    return [PartitionRule(prefix, P("fsdp", None, None, None))]
