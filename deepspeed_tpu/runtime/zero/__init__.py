"""ZeRO subsystem: sharded init, offload tiers, tiling.

(ref: deepspeed/runtime/zero/__init__.py exposing zero.Init etc.)
"""

from deepspeed_tpu.runtime.zero.init import materialize_sharded

# functional analog of the reference's zero.Init context manager
# (partition_parameters.py:548): params come into existence sharded
Init = materialize_sharded
