"""zero.Init equivalent: materialize parameters directly SHARDED.

Capability analog of the reference's ``deepspeed.zero.Init`` context
(ref: deepspeed/runtime/zero/partition_parameters.py:548 — a metaclass
hook that partitions each parameter at module construction so no rank
ever holds the full model). The JAX-native form: jit the init function
with sharded output layouts, so each device materializes ONLY its own
shard of every parameter — peak per-device memory during init is the
shard size, never the full tensor, and no host-side full copy exists.
"""

from typing import Any, Callable, Optional, Sequence

import jax

from deepspeed_tpu.parallel import sharding as sharding_lib

PyTree = Any


def materialize_sharded(init_fn: Callable[[jax.Array], PyTree],
                        rng: jax.Array,
                        mesh,
                        zero_stage: int = 3,
                        rules: Optional[Sequence] = None,
                        min_shard_size: int = 1024) -> PyTree:
    """Run ``init_fn(rng) -> params`` under jit with ZeRO/TP output
    shardings: every leaf comes into existence already partitioned over
    the mesh (the zero.Init semantics — partition at construction,
    ref partition_parameters.py:548 / _convert_to_deepspeed_param :771).

    Use for models whose full fp32 tree exceeds one device (or host
    process) — combined with ``deepspeed_tpu.initialize(...)`` the full
    tree never exists anywhere.
    """
    shapes = jax.eval_shape(init_fn, rng)
    pspecs = sharding_lib.param_specs(
        shapes, mesh, zero_stage=zero_stage, rules=list(rules or []),
        min_shard_size=min_shard_size)
    shardings = sharding_lib.to_named(pspecs, mesh)
    return jax.jit(init_fn, out_shardings=shardings)(rng)
