"""ZeRO-Offload / ZeRO-Infinity: host-RAM and NVMe optimizer-state tiering.

TPU-native re-engineering of the reference's offload path
(ref: deepspeed/runtime/zero/stage_1_and_2.py:1005
 async_accumulate_grad_in_cpu_via_gpu + step path :1725-1735 stepping
 DeepSpeedCPUAdam on pinned CPU buffers; NVMe via
 runtime/swap_tensor/partitioned_optimizer_swapper.py).

Architecture on TPU:
- the DEVICE holds only compute-dtype (bf16) parameters; the fp32 master
  weights and Adam moments live on HOST (numpy) — device HBM per param is
  2 bytes instead of the 16 (fp32 master + m + v + param) of the fused path.
- the jitted step computes loss + fp32 grads only; grads stream
  device->host, the native AVX Adam (ops/cpu_adam) updates the master
  weights while simultaneously rounding them to bf16 into a staging buffer
  (one memory pass), and the staged bf16 params stream host->device.
- with ``device: nvme`` the moments live in per-leaf files and are swapped
  through :class:`PipelinedOptimizerSwapper`, double-buffered so leaf
  ``i+1`` reads while ``i`` computes — the reference's pipelined swapper
  loop (pipelined_optimizer_swapper.py:60), re-timed for host cores.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import logger

PyTree = Any


class HostOffloadOptimizer:
    """Host-resident Adam over a pytree of parameters.

    Parameters stay leaf-partitioned (each leaf = one "subgroup" in the
    reference's sense, stage3.py:1259 _optimizer_step loops subgroups the
    same way).
    """

    def __init__(self, params_fp32: PyTree, lr_schedule: Callable,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 nvme_path: Optional[str] = None,
                 pipeline_swap: bool = True,
                 param_dtype=jnp.bfloat16):
        self.lr_schedule = lr_schedule
        self.adam = DeepSpeedCPUAdam(betas=betas, eps=eps,
                                     weight_decay=weight_decay,
                                     adamw_mode=adamw_mode)
        self.param_dtype = param_dtype
        leaves, self.treedef = jax.tree_util.tree_flatten(params_fp32)
        self.shapes = [l.shape for l in leaves]
        # flat fp32 master copies on host
        self.master: List[np.ndarray] = [
            np.ascontiguousarray(np.asarray(l, np.float32).ravel())
            for l in leaves]
        self.staging: List[np.ndarray] = [
            np.empty(m.size, np.uint16) for m in self.master]
        self.step_count = 0

        self.swapper = None
        if nvme_path is not None:
            from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
                OptimizerStateSwapper, PipelinedOptimizerSwapper)
            cls = PipelinedOptimizerSwapper if pipeline_swap \
                else OptimizerStateSwapper
            self.swapper = cls(nvme_path, n_tensors=2)
            # moments start as zeros on disk
            for i, m in enumerate(self.master):
                z = np.zeros(m.size, np.float32)
                self.swapper.swap_out(str(i), [z, z])
        self._pipelined = pipeline_swap and self.swapper is not None

    def device_params(self) -> PyTree:
        """Compute-dtype param pytree for the device."""
        leaves = [jnp.asarray(m.reshape(s), jnp.float32).astype(self.param_dtype)
                  for m, s in zip(self.master, self.shapes)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def step(self, grads: PyTree, lr: Optional[float] = None) -> PyTree:
        """Apply one Adam step from host-side grads; returns the updated
        compute-dtype param pytree (numpy-backed, ready to device_put)."""
        self.step_count += 1
        lr = float(self.lr_schedule(self.step_count - 1)) if lr is None else lr
        glat = [np.ascontiguousarray(np.asarray(g, np.float32).ravel())
                for g in jax.tree_util.tree_leaves(grads)]
        assert len(glat) == len(self.master)

        n = len(self.master)
        for i in range(n):
            key = str(i)
            if self.swapper is not None:
                m, v = self.swapper.swap_in(key)
                self.adam.load_state(key, self.step_count - 1, m, v)
                if self._pipelined and i + 1 < n:
                    self.swapper.prefetch(str(i + 1))
            self.adam.step(key, self.master[i], glat[i], lr=lr,
                           params_bf16_out=self.staging[i])
            if self.swapper is not None:
                st = self.adam.state_arrays(key)
                if self._pipelined:
                    self.swapper.swap_out_async(
                        key, [st["exp_avg"], st["exp_avg_sq"]])
                else:
                    self.swapper.swap_out(
                        key, [st["exp_avg"], st["exp_avg_sq"]])
                # free host copies of the moments — they live on NVMe now
                del self.adam.state[key]
        if self.swapper is not None and self._pipelined:
            self.swapper.finish()

        if self.param_dtype == jnp.bfloat16:
            leaves = [s.view(jnp.bfloat16.dtype).reshape(shape)
                      for s, shape in zip(self.staging, self.shapes)]
        else:
            leaves = [m.astype(np.dtype(self.param_dtype)).reshape(shape)
                      for m, shape in zip(self.master, self.shapes)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def reset_from_params(self, params: PyTree):
        """Re-seed the fp32 masters from a (restored) param pytree and zero
        the moments — used when a checkpoint has no host optimizer state."""
        leaves = jax.tree_util.tree_leaves(params)
        assert len(leaves) == len(self.master)
        self.master = [
            np.ascontiguousarray(np.asarray(l, np.float32).ravel())
            for l in leaves]
        self.adam.state.clear()
        if self.swapper is not None:
            for i, m in enumerate(self.master):
                z = np.zeros(m.size, np.float32)
                self.swapper.swap_out(str(i), [z, z])

    # --- checkpointing hooks -----------------------------------------
    def state_dict(self) -> Dict:
        states = {}
        for i in range(len(self.master)):
            key = str(i)
            if self.swapper is not None and self.swapper.has_state(key):
                m, v = self.swapper.swap_in(key)
            elif key in self.adam.state:
                st = self.adam.state[key]
                m, v = st["exp_avg"], st["exp_avg_sq"]
            else:
                m = v = np.zeros(self.master[i].size, np.float32)
            states[key] = {"exp_avg": np.array(m), "exp_avg_sq": np.array(v)}
        return {"step": self.step_count, "master": self.master,
                "state": states}

    def load_state_dict(self, sd: Dict):
        self.step_count = int(sd["step"])
        self.master = [np.ascontiguousarray(m, np.float32)
                       for m in sd["master"]]
        for key, st in sd["state"].items():
            if self.swapper is not None:
                self.swapper.swap_out(key, [st["exp_avg"], st["exp_avg_sq"]])
            else:
                self.adam.load_state(key, self.step_count, st["exp_avg"],
                                     st["exp_avg_sq"])
