"""ZeRO-Offload / ZeRO-Infinity: host-RAM and NVMe optimizer-state tiering.

TPU-native re-engineering of the reference's offload path
(ref: deepspeed/runtime/zero/stage_1_and_2.py:1005
 async_accumulate_grad_in_cpu_via_gpu + step path :1725-1735 stepping
 DeepSpeedCPUAdam on pinned CPU buffers; NVMe via
 runtime/swap_tensor/partitioned_optimizer_swapper.py).

Architecture on TPU:
- the DEVICE holds only compute-dtype (bf16) parameters; the fp32 master
  weights and optimizer moments live on HOST — device HBM per param is
  2 bytes instead of the 16 (fp32 master + m + v + param) of the fused
  path.
- masters are stored **per device shard**: each unique shard of a leaf's
  sharding gets its own flat fp32 master + state key, so ZeRO-sharded
  (fsdp/data-partitioned) parameters offload partition-wise exactly like
  the reference's per-DP-rank partitions (stage_1_and_2.py:546), and on
  multi-host meshes every process steps only the shards it can address —
  updated leaves are rebuilt with
  ``jax.make_array_from_single_device_arrays``, the multi-host-correct
  assembly path.
- the step is a 3-stage host pipeline: every shard's device->host copy is
  launched async up front (``copy_to_host_async``), the native AVX
  Adam/Adagrad then crunches shard-by-shard while later shards are still
  in flight, and each updated bf16 shard's host->device DMA is enqueued
  immediately (``jax.device_put`` is async) — transfers hide behind
  compute in both directions, the reference's overlap design
  (stage_1_and_2.py:1005, pipelined_optimizer_swapper.py:60) re-timed for
  host cores.
- with ``device: nvme`` the moments live in per-shard files and are
  swapped through :class:`PipelinedOptimizerSwapper`, double-buffered so
  shard ``i+1`` reads while ``i`` computes.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdagrad, DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import logger

PyTree = Any

# --- test/benchmark seams -------------------------------------------------
# _pipeline_probe(event, leaf_idx, shard_key): called at "d2h_enqueue"
# (stage 1, transfer launched), "adam_done" (stage 2) and "h2d_enqueue"
# (stage 3) — lets tests assert the overlap schedule (all d2h enqueued
# before the first Adam; shard k's h2d in flight before k+1's Adam ends)
# without patching jax internals, and lets the loopback benchmark
# (tools/offload_loopback.py) timestamp the real schedule under a
# synthetic link. _read_shard(leaf_idx, shard_key, raw) gates the
# stage-2 materialization of a d2h transfer — the loopback benchmark
# substitutes a rate-limited wait to emulate a PCIe-speed link.
_pipeline_probe: Optional[Callable[[str, int, str], None]] = None
_read_shard: Optional[Callable[[int, str, Any], Any]] = None


def _index_key(idx: Tuple) -> str:
    """Stable string key for a shard's global index (tuple of slices)."""
    return ";".join(f"{s.start or 0}:{s.stop}" for s in idx)


class _LeafShards:
    """Per-leaf shard table derived from its sharding: unique shard
    indices, the devices holding each, and the shard shapes."""

    def __init__(self, shape, sharding):
        self.shape = tuple(shape)
        self.sharding = sharding
        self.by_key: Dict[str, Dict] = {}
        if sharding is None:
            dev = jax.devices()[0]
            self.by_key["full"] = {
                "index": tuple(slice(0, n) for n in self.shape),
                "devices": [dev], "shape": self.shape}
            return
        imap = sharding.addressable_devices_indices_map(self.shape)
        for dev, idx in imap.items():
            idx = tuple(idx) if idx is not None else tuple(
                slice(0, n) for n in self.shape)
            # normalize unbounded slices
            idx = tuple(slice(s.start or 0,
                              s.stop if s.stop is not None else n)
                        for s, n in zip(idx, self.shape))
            k = _index_key(idx)
            ent = self.by_key.setdefault(
                k, {"index": idx, "devices": [],
                    "shape": tuple(s.stop - s.start for s in idx)})
            ent["devices"].append(dev)


class HostOffloadOptimizer:
    """Host-resident Adam/Adagrad over a pytree of (possibly sharded)
    parameters. Each (leaf, shard) pair is one state subgroup — the
    analog of the reference's per-partition optimizer state
    (stage3.py:1259 _optimizer_step loops subgroups the same way).
    """

    def __init__(self, params_fp32: PyTree, lr_schedule: Callable,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 nvme_path: Optional[str] = None,
                 pipeline_swap: bool = True,
                 param_dtype=jnp.bfloat16,
                 shardings: Optional[PyTree] = None,
                 optimizer: str = "adam"):
        self.lr_schedule = lr_schedule
        self.optimizer_name = optimizer
        if optimizer == "adagrad":
            self.opt = DeepSpeedCPUAdagrad(eps=eps,
                                           weight_decay=weight_decay)
        else:
            self.opt = DeepSpeedCPUAdam(betas=betas, eps=eps,
                                        weight_decay=weight_decay,
                                        adamw_mode=adamw_mode)
        if optimizer == "adagrad" and nvme_path is not None:
            raise ValueError(
                "NVMe moment swapping supports Adam only (the reference's "
                "swappable-optimizer set, ref zero/utils.py)")
        self.param_dtype = param_dtype
        leaves, self.treedef = jax.tree_util.tree_flatten(params_fp32)
        shard_leaves = (jax.tree_util.tree_leaves(shardings)
                        if shardings is not None else [None] * len(leaves))
        assert len(shard_leaves) == len(leaves)
        self.shapes = [np.asarray(l).shape for l in leaves]
        self.tables: List[_LeafShards] = []
        # flat fp32 master copies on host, one per (leaf, unique shard)
        self.master: List[Dict[str, np.ndarray]] = []
        self.staging: List[Dict[str, np.ndarray]] = []
        for l, sh, shape in zip(leaves, shard_leaves, self.shapes):
            table = _LeafShards(shape, sh)
            full = np.asarray(l, np.float32)
            m: Dict[str, np.ndarray] = {}
            st: Dict[str, np.ndarray] = {}
            for k, ent in table.by_key.items():
                piece = np.ascontiguousarray(full[ent["index"]].ravel())
                m[k] = piece
                st[k] = np.empty(piece.size, np.uint16)
            self.tables.append(table)
            self.master.append(m)
            self.staging.append(st)
        self.step_count = 0

        self.swapper = None
        if nvme_path is not None:
            from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
                OptimizerStateSwapper, PipelinedOptimizerSwapper)
            cls = PipelinedOptimizerSwapper if pipeline_swap \
                else OptimizerStateSwapper
            self.swapper = cls(nvme_path, n_tensors=2)
            # moments start as zeros on disk
            for i, m in enumerate(self.master):
                for k, piece in m.items():
                    z = np.zeros(piece.size, np.float32)
                    self.swapper.swap_out(f"{i}:{k}", [z, z])
        self._pipelined = pipeline_swap and self.swapper is not None

    # ------------------------------------------------------------------
    def _assemble_leaf(self, i: int, per_key_np: Dict[str, np.ndarray]):
        """Host shard values -> device array on the leaf's sharding
        (multi-host correct: only addressable shards are supplied)."""
        table = self.tables[i]
        if table.sharding is None:
            dev = table.by_key["full"]["devices"][0]
            return jax.device_put(
                per_key_np["full"].reshape(self.shapes[i]), dev)
        arrs = []
        for k, ent in table.by_key.items():
            piece = per_key_np[k].reshape(ent["shape"])
            for dev in ent["devices"]:
                arrs.append(jax.device_put(piece, dev))
            if _pipeline_probe is not None:
                _pipeline_probe("h2d_enqueue", i, k)
        return jax.make_array_from_single_device_arrays(
            self.shapes[i], table.sharding, arrs)

    def device_params(self) -> PyTree:
        """Compute-dtype param pytree placed on the mesh."""
        out = []
        for i, m in enumerate(self.master):
            staged = {k: np.asarray(
                piece.astype(np.float32), np.float32)
                .astype(jnp.asarray(0, self.param_dtype).dtype)
                for k, piece in m.items()}
            out.append(self._assemble_leaf(i, staged))
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # ------------------------------------------------------------------
    def step(self, grads: PyTree, lr: Optional[float] = None) -> PyTree:
        """One optimizer step from (sharded) device grads; returns the
        updated compute-dtype param pytree placed back on the mesh.

        3-stage pipeline: async d2h for every shard up front, native
        optimizer shard-by-shard, async h2d of each updated shard."""
        self.step_count += 1
        lr = float(self.lr_schedule(self.step_count - 1)) if lr is None else lr
        g_leaves = jax.tree_util.tree_leaves(grads)
        assert len(g_leaves) == len(self.master)

        # stage 1: launch every shard's d2h copy (non-blocking)
        shard_data: List[Dict[str, Any]] = []
        for li, (g, table) in enumerate(zip(g_leaves, self.tables)):
            d: Dict[str, Any] = {}
            if isinstance(g, jax.Array):
                for sh in g.addressable_shards:
                    idx = tuple(slice(s.start or 0,
                                      s.stop if s.stop is not None
                                      else n)
                                for s, n in zip(sh.index, g.shape))
                    k = _index_key(idx)
                    if k not in d and k in table.by_key:
                        try:
                            sh.data.copy_to_host_async()
                        except Exception:   # dslint: disable=DS006 — best-effort async hint; stage 2's materialization is the correctness path
                            pass
                        if _pipeline_probe is not None:
                            _pipeline_probe("d2h_enqueue", li, k)
                        d[k] = sh.data
                if len(d) != len(table.by_key):
                    # grad sharding does not line up with the param shard
                    # table (e.g. replicated grads over sharded params):
                    # fall back to slicing the global value, loudly
                    # correct rather than silently wrong
                    # dslint: disable=DS001 — deliberate sync pull on the slow fallback path
                    full = np.asarray(g, np.float32)
                    d = {k: full[ent["index"]]
                         for k, ent in table.by_key.items()}
            else:
                # non-jax leaf (already host): asarray is a view, no sync
                full = np.asarray(g, np.float32)  # dslint: disable=DS001
                for k, ent in table.by_key.items():
                    d[k] = full[ent["index"]]
            shard_data.append(d)

        # stage 2+3: native optimizer per shard; h2d enqueued immediately
        out_leaves = []
        bf16 = jnp.asarray(0, jnp.bfloat16).dtype
        n_items = len(self.master)
        for i in range(n_items):
            table = self.tables[i]
            staged_np: Dict[str, np.ndarray] = {}
            for k in table.by_key:
                skey = f"{i}:{k}"
                mst = self.master[i][k]
                raw = shard_data[i][k]
                if _read_shard is not None:
                    raw = _read_shard(i, k, raw)
                # the stage-2 materialization of the d2h copy stage 1
                # already launched async — THIS wait is the pipeline, not
                # a stray sync: later shards are still in flight behind it
                g_np = np.ascontiguousarray(
                    np.asarray(raw, np.float32).ravel())  # dslint: disable=DS001
                assert g_np.size == mst.size, (
                    f"grad shard {skey}: {g_np.size} elems vs master "
                    f"{mst.size} — grad/param sharding mismatch")
                if self.swapper is not None:
                    m, v = self.swapper.swap_in(skey)
                    self.opt.load_state(skey, self.step_count - 1, m, v)
                    nxt = self._next_swap_key(i, k)
                    if self._pipelined and nxt is not None:
                        self.swapper.prefetch(nxt)
                if self.optimizer_name == "adagrad":
                    self.opt.step(skey, mst, g_np, lr=lr)
                    stg = mst.astype(bf16)
                else:
                    self.opt.step(skey, mst, g_np, lr=lr,
                                  params_bf16_out=self.staging[i][k])
                    stg = self.staging[i][k].view(bf16)
                if _pipeline_probe is not None:
                    _pipeline_probe("adam_done", i, k)
                if self.param_dtype == jnp.bfloat16:
                    staged_np[k] = stg
                else:
                    staged_np[k] = mst.astype(np.dtype(self.param_dtype))
                if self.swapper is not None:
                    st = self.opt.state_arrays(skey)
                    payload = [st["exp_avg"], st["exp_avg_sq"]]
                    if self._pipelined:
                        self.swapper.swap_out_async(skey, payload)
                    else:
                        self.swapper.swap_out(skey, payload)
                    del self.opt.state[skey]
            out_leaves.append(self._assemble_leaf(i, staged_np))
        if self.swapper is not None and self._pipelined:
            self.swapper.finish()
        return jax.tree_util.tree_unflatten(self.treedef, out_leaves)

    def _next_swap_key(self, i: int, k: str) -> Optional[str]:
        keys = list(self.tables[i].by_key)
        j = keys.index(k)
        if j + 1 < len(keys):
            return f"{i}:{keys[j+1]}"
        if i + 1 < len(self.tables):
            return f"{i+1}:{list(self.tables[i+1].by_key)[0]}"
        return None

    # ------------------------------------------------------------------
    def reset_from_params(self, params: PyTree):
        """Re-seed the fp32 masters from a (restored) param pytree and zero
        the moments — used when a checkpoint has no host optimizer state."""
        leaves = jax.tree_util.tree_leaves(params)
        assert len(leaves) == len(self.master)
        for i, (l, table) in enumerate(zip(leaves, self.tables)):
            full = np.asarray(l, np.float32)
            for k, ent in table.by_key.items():
                self.master[i][k] = np.ascontiguousarray(
                    full[ent["index"]].ravel())
        self.opt.state.clear()
        if self.swapper is not None:
            for i, m in enumerate(self.master):
                for k, piece in m.items():
                    z = np.zeros(piece.size, np.float32)
                    self.swapper.swap_out(f"{i}:{k}", [z, z])

    # --- multi-host checkpointing: per-shard region pieces ------------
    def _shard_moments(self, i: int, k: str):
        skey = f"{i}:{k}"
        n = self.master[i][k].size
        if self.swapper is not None and self.swapper.has_state(skey):
            m, v = self.swapper.swap_in(skey)
            return np.asarray(m, np.float32), np.asarray(v, np.float32)
        if skey in self.opt.state:
            st = self.opt.state[skey]
            m = st.get("exp_avg")
            v = st.get("exp_avg_sq")
            m = (np.asarray(m, np.float32) if m is not None and m.size
                 else np.zeros(n, np.float32))
            return m, np.asarray(v, np.float32)
        return np.zeros(n, np.float32), np.zeros(n, np.float32)

    def shard_export(self) -> List[Dict]:
        """Pieces for the shards THIS process addresses — the multi-host
        save path (analog of the reference's per-DP-rank
        optim_states.pt shards, engine.py:2327). Restoring merges every
        process's pieces, so any topology can load any other's save."""
        out = []
        for i, table in enumerate(self.tables):
            for k, ent in table.by_key.items():
                m, v = self._shard_moments(i, k)
                out.append({
                    "leaf": np.asarray(i),
                    "starts": np.asarray([s.start for s in ent["index"]]),
                    "stops": np.asarray([s.stop for s in ent["index"]]),
                    "master": self.master[i][k],
                    "exp_avg": m, "exp_avg_sq": v})
        return out

    def shard_import(self, pieces: List[Dict], step: int):
        """Merge exported shard pieces (from any number of processes at
        any save-time topology) into this instance's masters/moments."""
        g_master = [np.zeros(s, np.float32) for s in self.shapes]
        g_m = [np.zeros(s, np.float32) for s in self.shapes]
        g_v = [np.zeros(s, np.float32) for s in self.shapes]
        for p in pieces:
            i = int(p["leaf"])
            idx = tuple(slice(int(a), int(b))
                        for a, b in zip(p["starts"], p["stops"]))
            shp = tuple(s.stop - s.start for s in idx)
            g_master[i][idx] = np.asarray(p["master"],
                                          np.float32).reshape(shp)
            g_m[i][idx] = np.asarray(p["exp_avg"], np.float32).reshape(shp)
            g_v[i][idx] = np.asarray(p["exp_avg_sq"],
                                     np.float32).reshape(shp)
        self.load_state_dict({
            "step": step,
            "master": [m.ravel() for m in g_master],
            "state": {str(i): {"exp_avg": g_m[i].ravel(),
                               "exp_avg_sq": g_v[i].ravel()}
                      for i in range(len(self.shapes))}})

    # --- checkpointing hooks -----------------------------------------
    def _global_master(self, i: int) -> np.ndarray:
        """Assemble the full fp32 master for leaf i from its shards
        (host-side consolidation, the zero_to_fp32 analog)."""
        full = np.zeros(self.shapes[i], np.float32)
        for k, ent in self.tables[i].by_key.items():
            full[ent["index"]] = self.master[i][k].reshape(ent["shape"])
        return full.ravel()

    def _global_moment(self, i: int, which: str) -> np.ndarray:
        """Assemble a full per-leaf moment from its shard states —
        checkpoints are topology-INDEPENDENT (elastic: saved at any shard
        layout, restorable at any other, matching the reference's elastic
        ZeRO checkpoints, stage_1_and_2.py:2074)."""
        full = np.zeros(self.shapes[i], np.float32)
        for k, ent in self.tables[i].by_key.items():
            skey = f"{i}:{k}"
            if self.swapper is not None and self.swapper.has_state(skey):
                m, v = self.swapper.swap_in(skey)
                piece = m if which == "exp_avg" else v
            elif skey in self.opt.state:
                st = self.opt.state[skey]
                piece = st.get(which)
                if piece is None or piece.size == 0:
                    continue
            else:
                continue
            full[ent["index"]] = np.asarray(piece, np.float32).reshape(
                ent["shape"])
        return full.ravel()

    def state_dict(self) -> Dict:
        states = {}
        for i in range(len(self.master)):
            states[str(i)] = {
                "exp_avg": self._global_moment(i, "exp_avg"),
                "exp_avg_sq": self._global_moment(i, "exp_avg_sq")}
        return {"step": self.step_count,
                "master": [self._global_master(i)
                           for i in range(len(self.master))],
                "state": states}

    def load_state_dict(self, sd: Dict):
        self.step_count = int(sd["step"])
        for i, flat in enumerate(sd["master"]):
            full = np.asarray(flat, np.float32).reshape(self.shapes[i])
            for k, ent in self.tables[i].by_key.items():
                self.master[i][k] = np.ascontiguousarray(
                    full[ent["index"]].ravel())
        for key, st in sd["state"].items():
            i = int(key)
            m_full = np.asarray(st["exp_avg"], np.float32)
            v_full = np.asarray(st["exp_avg_sq"], np.float32)
            m_full = m_full.reshape(self.shapes[i]) if m_full.size else None
            v_full = v_full.reshape(self.shapes[i])
            for k2, ent in self.tables[i].by_key.items():
                skey = f"{i}:{k2}"
                v_piece = np.ascontiguousarray(
                    v_full[ent["index"]].ravel())
                m_piece = (np.ascontiguousarray(
                    m_full[ent["index"]].ravel()) if m_full is not None
                    else np.zeros_like(v_piece))
                if self.swapper is not None:
                    self.swapper.swap_out(skey, [m_piece, v_piece])
                else:
                    self.opt.load_state(skey, self.step_count, m_piece,
                                        v_piece)

    # back-compat: some callers poke .adam directly
    @property
    def adam(self):
        return self.opt
