"""ZeRO-Infinity parameter tier: train models LARGER than device HBM.

Capability analog of the reference's partitioned-parameter swapping
(ref: deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:37
AsyncPartitionedParameterSwapper — fp16 param partitions staged
GPU<->CPU<->NVMe; driven from runtime/zero/stage3.py:226 +
partition_parameters.py:548), which is what makes "13B params on one
32GB V100" possible (ref docs/_pages/features.md:116).

TPU-native re-engineering. The reference hooks torch module forwards and
swaps param partitions in/out of a dynamic allocator. Under XLA the
design inverts: the model is expressed as a LAYERED program (embed ->
N identical layer applications -> head) and the runtime streams
**groups of layers** — each group one jitted ``lax.scan`` over its
stacked weights — so the device only ever holds the working set: the
current + prefetched group's bf16 block, the inter-group activations,
and the embed/head ("other") weights. The full parameter set lives on
HOST RAM as per-group blocks with fp32 masters, Adam moments on host or
NVMe (through the aio-backed pipelined swapper):

- forward:  x = embed(other, batch); for g in groups:
  x_g saved, x = scan(layer_fn, x, P_g) with P_{g+1}'s host->device DMA
  in flight behind the group's compute (double-buffered jax.device_put).
- backward: for g in reverse: (dx, dP_g) = vjp(group)(P_g, x_g, dx) —
  layers recompute inside the scan's VJP (activation checkpointing at
  layer granularity), dP_g streams device->host asynchronously
  (copy_to_host_async) while group g-1's backward runs.
- update:   host AVX Adam (ops/cpu_adam, the C++ kernel) steps each
  group's fp32 master from the accumulated host grads and re-rounds to
  bf16 in one pass; gradient clipping uses per-group squared norms
  summed into the exact global norm before any update (matching the
  reference's two-phase norm-then-step, stage_1_and_2.py:1670-1754).

Grouping exists because dispatch+DMA latency, not bandwidth, dominates
fine-grained streaming: one scan per ~0.5-1.5GB block amortizes the
per-call cost the way the reference's contiguous swap buffers amortize
pread granularity (partitioned_param_swapper.py aligned-buffer pool).

Device HBM footprint is O(2 groups + activations), independent of model
size — capacity is bounded by host RAM/NVMe, not HBM.
"""

import concurrent.futures as _futures
import math
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import log_dist

PyTree = Any

# auto group sizing: aim for <= ~8 streamed blocks, capped per-block bytes
_TARGET_GROUPS = 8
_GROUP_BYTES_CAP = 1_500_000_000


@dataclass
class LayeredModel:
    """Contract for parameter-streaming training (the analog of the
    reference's PipelineModule layer-list contract, runtime/pipe/module.py:87
    — a model the runtime can execute one layer at a time).

    split_params(params) -> (stacked_block, other): separate the L-stacked
        per-layer weights (leading axis = layer) from everything else
        (embeddings, final norm, head).
    embed_fn(other, batch) -> (x, aux): input embedding; ``aux`` is carried
        to the head (e.g. shifted targets).
    layer_fn(layer_params, x) -> x: ONE layer (unstacked leaves).
    head_fn(other, x, aux) -> loss: final norm + head + loss.
    layer_remat_policy: optional jax.checkpoint policy for the in-group
        backward recompute (None = recompute everything).
    """
    split_params: Callable[[PyTree], Tuple[PyTree, PyTree]]
    embed_fn: Callable[[PyTree, PyTree], Tuple[jnp.ndarray, Any]]
    layer_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray]
    head_fn: Callable[[PyTree, jnp.ndarray, Any], jnp.ndarray]
    n_layers: int = 0
    layer_remat_policy: Any = None
    # join(stacked_block, other) -> full params (inverse of split_params);
    # default assumes the GPT dict layout {"block": ..., **other}
    join_params: Optional[Callable[[PyTree, PyTree], PyTree]] = None


def _flat_f32(tree: PyTree) -> List[np.ndarray]:
    return [np.ascontiguousarray(np.asarray(l, np.float32).ravel())
            for l in jax.tree_util.tree_leaves(tree)]


class InfinityParamEngine:
    """Single-chip trainer whose parameters live on host, streamed in
    layer groups (see module docstring). Public surface mirrors
    DeepSpeedEngine.train_batch / state_dict / load_state_dict.
    """

    def __init__(self, layered: LayeredModel, params: PyTree, config,
                 lr_schedule: Callable[[int], float]):
        self.layered = layered
        self.config = config
        self.lr_schedule = lr_schedule
        # fp16 runs the reference's loss-scaled scheme host-side: the
        # backward seed is scaled on device, the per-group grad pulls
        # land scaled fp32 on host, and the update phase unscales + folds
        # the overflow check into the global-norm pass it already does
        # (a non-finite norm IS the overflow signal — no extra sweep).
        # (ref: partitioned_param_swapper.py:37 stages fp16 partitions;
        #  ref runtime/fp16/loss_scaler.py DynamicLossScaler semantics)
        self.fp16 = bool(config.fp16.enabled)
        self.compute_dtype = jnp.float16 if self.fp16 else jnp.bfloat16
        fp = config.fp16
        if self.fp16 and fp.loss_scale == 0:          # dynamic
            self.cur_scale = 2.0 ** fp.initial_scale_power
            self._dynamic_scale = True
        else:
            self.cur_scale = fp.loss_scale if self.fp16 else 1.0
            self._dynamic_scale = False
        self.scale_window = fp.loss_scale_window
        self.min_scale = fp.min_loss_scale
        self._hyst_left = fp.hysteresis
        self._hysteresis = fp.hysteresis
        self._good_steps = 0
        self.skipped_steps = 0
        self.clip = config.gradient_clipping
        self.gas = config.gradient_accumulation_steps

        off = config.zero.offload_optimizer
        opt = dict(config.optimizer.params or {})
        name = (config.optimizer.type or "adamw").lower()
        if name not in ("adam", "adamw"):
            raise ValueError(
                f"param offload supports the Adam family, got {name!r}")
        self.adam = DeepSpeedCPUAdam(
            betas=tuple(opt.get("betas", (0.9, 0.999))),
            eps=opt.get("eps", 1e-8),
            weight_decay=opt.get("weight_decay", 0.0),
            adamw_mode=(name == "adamw" or opt.get("adam_w_mode", True)))

        # Two input forms: a full parameter pytree, or a FACTORY
        # callable(i | "other") -> per-layer fp32 pytree — the factory form
        # never materializes the stacked tree, so host peak stays lower
        # (needed at the 13B scale, where the reference likewise
        # materializes partitions lazily under zero.Init,
        # ref partition_parameters.py:548).
        if callable(params):
            L = layered.n_layers
            assert L > 0, "factory form needs LayeredModel.n_layers"

            def _layer_slice(i):
                return params(i)

            other = params("other")
        else:
            block, other = layered.split_params(params)
            leaves = jax.tree_util.tree_leaves(block)
            L = layered.n_layers or (leaves[0].shape[0] if leaves else 0)

            def _layer_slice(i):
                return jax.tree_util.tree_map(lambda a: np.asarray(a[i]),
                                              block)

        assert L > 0, "LayeredModel with no layers"
        self.n_layers = L

        # --- group sizing -------------------------------------------------
        first = _layer_slice(0)
        self.block_treedef = jax.tree_util.tree_structure(first)
        layer_bytes = sum(np.asarray(l).size * 2
                          for l in jax.tree_util.tree_leaves(first))
        g = config.zero.offload_param.stream_group_layers
        if g <= 0:
            g = max(1, math.ceil(L / _TARGET_GROUPS))
            if layer_bytes * g > _GROUP_BYTES_CAP:
                g = max(1, _GROUP_BYTES_CAP // max(layer_bytes, 1))
        self.group_size = int(g)
        bounds = list(range(0, L, self.group_size)) + [L]
        self.groups: List[range] = [range(bounds[i], bounds[i + 1])
                                    for i in range(len(bounds) - 1)]
        self.n_groups = len(self.groups)
        # back-compat alias (number of streamed blocks)
        self.L = self.n_groups

        # --- host parameter store: per-group stacked bf16 + fp32 masters
        self.host_bf16: List[List[np.ndarray]] = []
        self.master: List[List[np.ndarray]] = []   # fp32, flat per leaf
        self.shapes: List[List[tuple]] = []        # stacked (g, ...) shapes
        self.grad_acc: List[Optional[List[np.ndarray]]] = [None] * self.n_groups
        self.staging: List[List[np.ndarray]] = []
        for gi, grp in enumerate(self.groups):
            slices = [first if i == 0 else _layer_slice(i) for i in grp]
            stacked = [np.stack([np.asarray(
                jax.tree_util.tree_leaves(s)[j], np.float32)
                for s in slices])
                for j in range(len(jax.tree_util.tree_leaves(slices[0])))]
            del slices
            self.shapes.append([a.shape for a in stacked])
            self.master.append([np.ascontiguousarray(a.ravel())
                                for a in stacked])
            self.host_bf16.append(
                [self._host_compute(m, s)
                 for m, s in zip(self.master[-1], self.shapes[-1])])
            self.staging.append(
                [np.empty(m.size, np.uint16) for m in self.master[-1]])
        del first

        # NVMe tier for the moments (ref pipelined_optimizer_swapper.py:60)
        self.swapper = None
        if off.enabled and off.device == "nvme":
            from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
                PipelinedOptimizerSwapper)
            assert off.nvme_path, "offload_optimizer.device=nvme needs nvme_path"
            self.swapper = PipelinedOptimizerSwapper(off.nvme_path,
                                                     n_tensors=2)
            for gi in range(self.n_groups):
                z = np.zeros(sum(m.size for m in self.master[gi]),
                             np.float32)
                self.swapper.swap_out(f"G{gi}", [z, z])

        # --- "other" params (embeddings/norm/head): device bf16 + host master
        self.other_master = _flat_f32(other)
        self.other_shapes = [np.asarray(l).shape
                             for l in jax.tree_util.tree_leaves(other)]
        self.other_treedef = jax.tree_util.tree_structure(other)
        self.other_staging = [np.empty(f.size, np.uint16)
                              for f in self.other_master]
        self.other_dev = self._other_to_device()
        self.other_grad_acc: Optional[List[np.ndarray]] = None
        del other

        self.step_count = 0
        self.global_steps = 0
        self._io = _futures.ThreadPoolExecutor(max_workers=1,
                                               thread_name_prefix="zinf-d2h")
        self._build_programs()
        n_params = sum(m.size for flat in self.master for m in flat) + \
            sum(f.size for f in self.other_master)
        self.n_params = n_params
        log_dist(
            f"ZeRO-Infinity param engine: {n_params/1e9:.2f}B params, "
            f"{L} layers in {self.n_groups} streamed groups of "
            f"{self.group_size}, host master "
            f"{sum(m.nbytes for flat in self.master for m in flat)/1e9:.1f}GB"
            f", moments={'nvme' if self.swapper else 'host'}", ranks=[0])

    # ------------------------------------------------------------------
    # jitted per-group programs
    # ------------------------------------------------------------------
    def _build_programs(self):
        layer_fn = self.layered.layer_fn
        embed_fn = self.layered.embed_fn
        head_fn = self.layered.head_fn
        policy = self.layered.layer_remat_policy

        def body(x, lp):
            return layer_fn(lp, x), None

        # always checkpoint at layer granularity inside the group scan —
        # the scan VJP then saves only the per-layer carries, and the
        # policy decides what else survives to the backward
        body = jax.checkpoint(body, policy=policy)

        def group_apply(gp, x):
            y, _ = jax.lax.scan(body, x, gp)
            return y

        def group_grad(gp, x, dy):
            # recompute-forward + backward fused in one program
            _, vjp = jax.vjp(group_apply, gp, x)
            dgp, dx = vjp(dy)
            return dx, dgp

        def head_grad(other, x, aux, scale):
            # `scale` seeds the backward with the fp16 loss scale (1.0
            # for bf16) — every downstream group grad arrives pre-scaled
            def f(o, xx):
                return head_fn(o, xx, aux)
            loss, vjp = jax.vjp(f, other, x)
            dother, dx = vjp(jnp.ones_like(loss) * scale.astype(loss.dtype))
            return loss, dx, dother

        def embed_grad(other, batch, dx0):
            def f(o):
                return embed_fn(o, batch)[0]
            _, vjp = jax.vjp(f, other)
            return vjp(dx0)[0]

        # NOTE: group_apply's x is NOT donated — the forward keeps every
        # group input alive in `acts` for the backward recompute.
        self._j_embed = jax.jit(embed_fn)
        self._j_group = jax.jit(group_apply)
        self._j_group_grad = jax.jit(group_grad, donate_argnums=(2,))
        self._j_head_grad = jax.jit(head_grad)
        self._j_embed_grad = jax.jit(embed_grad, donate_argnums=(2,))

    # ------------------------------------------------------------------
    # host <-> device staging
    # ------------------------------------------------------------------
    def _host_compute(self, m: np.ndarray, s: tuple) -> np.ndarray:
        """fp32 master -> host copy in the device compute dtype."""
        dt = np.float16 if self.fp16 else jnp.bfloat16.dtype
        return m.astype(dt).reshape(s)

    def _other_to_device(self) -> PyTree:
        leaves = [jnp.asarray(m.reshape(s), jnp.float32)
                  .astype(self.compute_dtype)
                  for m, s in zip(self.other_master, self.other_shapes)]
        return jax.tree_util.tree_unflatten(self.other_treedef, leaves)

    def _group_to_device(self, gi: int) -> PyTree:
        """Enqueue the h2d DMA for group gi's stacked bf16 block (async)."""
        leaves = [jax.device_put(a) for a in self.host_bf16[gi]]
        return jax.tree_util.tree_unflatten(self.block_treedef, leaves)

    def _grads_to_host(self, gi: int, dgp: PyTree) -> "_futures.Future":
        """Stream group gi's grads device->host and accumulate fp32."""
        leaves = list(jax.tree_util.tree_leaves(dgp))
        for l in leaves:
            try:
                l.copy_to_host_async()
            except Exception:   # dslint: disable=DS006 — best-effort async hint; the sync pull in _pull is the correctness path
                pass

        def _pull():
            acc = self.grad_acc[gi]
            if acc is None:
                acc = [np.zeros(int(np.prod(s)), np.float32)
                       for s in self.shapes[gi]]
                self.grad_acc[gi] = acc
            for a, l in zip(acc, leaves):
                a += np.asarray(l, np.float32).ravel()
            return gi

        return self._io.submit(_pull)

    # ------------------------------------------------------------------
    # one micro-batch: forward + streamed backward
    # ------------------------------------------------------------------
    def _micro_step(self, batch: PyTree) -> jnp.ndarray:
        G = self.n_groups
        x, aux = self._j_embed(self.other_dev, batch)

        # forward with double-buffered group prefetch
        acts: List[jnp.ndarray] = []
        cur = self._group_to_device(0)
        nxt = self._group_to_device(1) if G > 1 else None
        for gi in range(G):
            acts.append(x)
            x = self._j_group(cur, x)
            cur = nxt
            nxt = self._group_to_device(gi + 2) if gi + 2 < G else None

        loss, dx, dother = self._j_head_grad(
            self.other_dev, x, aux, jnp.float32(self.cur_scale))

        # backward, reverse streaming
        pulls = []
        cur = self._group_to_device(G - 1)
        nxt = self._group_to_device(G - 2) if G > 1 else None
        for gi in range(G - 1, -1, -1):
            dx, dgp = self._j_group_grad(cur, acts[gi], dx)
            pulls.append(self._grads_to_host(gi, dgp))
            del dgp
            cur = nxt
            nxt = self._group_to_device(gi - 2) if gi - 2 >= 0 else None
        acts.clear()

        dother_e = self._j_embed_grad(self.other_dev, batch, dx)
        # fold head-side + embed-side other-grads on host: both trees come
        # down in ONE batched transfer each (a per-leaf np.asarray loop
        # would block the dispatch queue once per leaf — the
        # _flush_monitor_buffer bug class, dslint DS001)
        head_np = jax.device_get(jax.tree_util.tree_leaves(dother))
        embed_np = jax.device_get(jax.tree_util.tree_leaves(dother_e))
        oleaves = [a.astype(np.float32).ravel() +
                   b.astype(np.float32).ravel()
                   for a, b in zip(head_np, embed_np)]
        if self.other_grad_acc is None:
            self.other_grad_acc = oleaves
        else:
            for a, g in zip(self.other_grad_acc, oleaves):
                a += g
        for f in pulls:
            f.result()
        return loss

    # ------------------------------------------------------------------
    # optimizer phase: exact global-norm clip, then per-group host Adam
    # ------------------------------------------------------------------
    def _apply_update(self):
        lr = float(self.lr_schedule(self.step_count))
        # unscale (fp16 loss scale; 1.0 under bf16) + grad-accum mean in
        # the same host pass that squares for the global norm
        inv = (1.0 / self.gas) / self.cur_scale

        # squared-norm terms accumulate as 0-d arrays; ONE float() after
        # the loop converts the lot (a per-leaf float() in the loop is the
        # dslint DS001 pattern — harmless on these host arrays, poison if
        # a leaf ever becomes device-resident)
        sq_terms = []
        for gi in range(self.n_groups):
            for g in self.grad_acc[gi]:
                if inv != 1.0:
                    g *= inv
                sq_terms.append(g @ g)
        for g in self.other_grad_acc:
            if inv != 1.0:
                g *= inv
            sq_terms.append(g @ g)
        sq = float(np.sum(sq_terms))
        gnorm = math.sqrt(sq) if sq >= 0.0 else float("nan")
        if not math.isfinite(gnorm):
            # overflow: drop the step and back the scale off — the
            # non-finite global norm IS the overflow check, no extra
            # sweep over the grads (ref DynamicLossScaler.update_scale)
            for gi in range(self.n_groups):
                self.grad_acc[gi] = None
            self.other_grad_acc = None
            self.skipped_steps += 1
            if self._dynamic_scale:
                self._hyst_left -= 1
                if self._hyst_left <= 0:
                    self.cur_scale = max(self.cur_scale / 2.0,
                                         self.min_scale)
                    self._hyst_left = self._hysteresis
                self._good_steps = 0
                log_dist(f"fp16 overflow, loss scale -> "
                         f"{self.cur_scale:.0f}", ranks=[0])
            return gnorm, lr, True
        self.step_count += 1
        if self.fp16 and self._dynamic_scale:
            self._good_steps += 1
            if self._good_steps >= self.scale_window:
                self.cur_scale *= 2.0
                self._good_steps = 0
                self._hyst_left = self._hysteresis
        scale = 1.0
        if self.clip > 0.0 and gnorm > self.clip:
            scale = self.clip / (gnorm + 1e-6)

        for gi in range(self.n_groups):
            key = f"G{gi}"
            master_leaves = self.master[gi]
            if self.swapper is not None:
                # moments stored concatenated per group on NVMe; split
                # back into per-leaf state slices
                m, v = self.swapper.swap_in(key)
                off = 0
                for j, f in enumerate(master_leaves):
                    self.adam.load_state(f"{key}.{j}", self.step_count - 1,
                                         m[off:off + f.size],
                                         v[off:off + f.size])
                    off += f.size
                if gi + 1 < self.n_groups:
                    self.swapper.prefetch(f"G{gi+1}")
            for j, (mst, g, stg) in enumerate(zip(
                    master_leaves, self.grad_acc[gi], self.staging[gi])):
                if scale != 1.0:
                    g *= scale
                self.adam.step(f"{key}.{j}", mst, g, lr=lr,
                               params_bf16_out=None if self.fp16 else stg)
            if self.fp16:
                # no fused fp16 copy-back in the AVX kernel — one extra
                # host pass converts the stepped master to fp16
                for j, (mst, s) in enumerate(zip(master_leaves,
                                                 self.shapes[gi])):
                    self.host_bf16[gi][j] = self._host_compute(mst, s)
            else:
                for j, (stg, s) in enumerate(zip(self.staging[gi],
                                                 self.shapes[gi])):
                    self.host_bf16[gi][j] = stg.view(jnp.bfloat16.dtype) \
                        .reshape(s).copy()
            if self.swapper is not None:
                ms, vs = [], []
                for j in range(len(master_leaves)):
                    st = self.adam.state_arrays(f"{key}.{j}")
                    ms.append(st["exp_avg"])
                    vs.append(st["exp_avg_sq"])
                    del self.adam.state[f"{key}.{j}"]
                self.swapper.swap_out_async(
                    key, [np.concatenate(ms), np.concatenate(vs)])
            self.grad_acc[gi] = None
        if self.swapper is not None:
            self.swapper.finish()

        for j, (mst, g, stg) in enumerate(zip(
                self.other_master, self.other_grad_acc,
                self.other_staging)):
            if scale != 1.0:
                g *= scale
            self.adam.step(f"other.{j}", mst, g, lr=lr,
                           params_bf16_out=None if self.fp16 else stg)
        self.other_grad_acc = None
        if self.fp16:
            leaves = [self._host_compute(m, shape)
                      for m, shape in zip(self.other_master,
                                          self.other_shapes)]
        else:
            leaves = [s.view(jnp.bfloat16.dtype).reshape(shape)
                      for s, shape in zip(self.other_staging,
                                          self.other_shapes)]
        self.other_dev = jax.device_put(
            jax.tree_util.tree_unflatten(self.other_treedef, leaves))
        return gnorm, lr, False

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def train_batch(self, batch: PyTree) -> Dict[str, Any]:
        """One optimizer step over a global batch; microbatches stream
        through the layered program (ref engine contract,
        runtime/engine.py train_batch)."""
        t0 = time.perf_counter()
        gas = self.gas
        if gas > 1:
            micro = jax.tree_util.tree_map(
                lambda a: a.reshape((gas, a.shape[0] // gas) + a.shape[1:]),
                batch)
            losses = []
            for s in range(gas):
                mb = jax.tree_util.tree_map(lambda a: a[s], micro)
                losses.append(self._micro_step(mb))
            # one batched pull for every micro-step's loss (per-loss
            # float() would round-trip the host once per micro-step)
            loss = float(np.mean(jax.device_get(losses)))
        else:
            loss = float(self._micro_step(batch))
        gnorm, lr, overflow = self._apply_update()
        self.global_steps += 1
        return {"loss": loss, "grad_norm": gnorm, "lr": lr,
                "overflow": overflow, "loss_scale": self.cur_scale,
                "step_time_s": time.perf_counter() - t0}

    def device_memory_bytes(self) -> int:
        """Approximate live HBM working set (other + 2 streamed groups)."""
        per_group = max(sum(a.nbytes for a in grp)
                        for grp in self.host_bf16)
        other = sum(int(np.prod(s)) * 2 for s in self.other_shapes)
        return other + 2 * per_group

    def gathered_params(self) -> PyTree:
        """Full bf16 param pytree (host-resident leaves), for eval or
        export — the analog of zero_to_fp32 consolidation
        (ref: utils/zero_to_fp32.py)."""
        n_leaves = len(self.host_bf16[0])
        stacked = [np.concatenate([self.host_bf16[gi][j]
                                   for gi in range(self.n_groups)], axis=0)
                   for j in range(n_leaves)]
        block = jax.tree_util.tree_unflatten(self.block_treedef, stacked)
        other = jax.tree_util.tree_unflatten(
            self.other_treedef,
            [self._host_compute(m, s)
             for m, s in zip(self.other_master, self.other_shapes)])
        if self.layered.join_params is not None:
            return self.layered.join_params(block, other)
        return {**other, "block": block}

    # --- checkpointing ------------------------------------------------
    def state_dict(self) -> Dict:
        states = {}
        for gi in range(self.n_groups):
            if self.swapper is not None:
                # moments live on NVMe concatenated per group — pull them
                # back so the checkpoint is self-contained
                if self.swapper.has_state(f"G{gi}"):
                    m, v = self.swapper.swap_in(f"G{gi}")
                    states[f"G{gi}"] = {"m": np.array(m), "v": np.array(v)}
                continue
            for j in range(len(self.master[gi])):
                key = f"G{gi}.{j}"
                if key in self.adam.state:
                    st = self.adam.state[key]
                    states[key] = {"m": np.array(st["exp_avg"]),
                                   "v": np.array(st["exp_avg_sq"])}
        for j in range(len(self.other_master)):
            key = f"other.{j}"
            if key in self.adam.state:
                st = self.adam.state[key]
                states[key] = {"m": np.array(st["exp_avg"]),
                               "v": np.array(st["exp_avg_sq"])}
        return {"step": self.step_count,
                "master": [list(m) for m in self.master],
                "other_master": list(self.other_master),
                "adam": states,
                "loss_scaler": {"cur_scale": self.cur_scale,
                                "good_steps": self._good_steps,
                                "hyst_left": self._hyst_left,
                                "skipped": self.skipped_steps}}

    def load_state_dict(self, sd: Dict):
        self.step_count = int(sd["step"])
        scaler = sd.get("loss_scaler")
        if scaler is not None:
            self.cur_scale = (float(scaler["cur_scale"]) if self.fp16
                              else 1.0)
            self._good_steps = int(scaler["good_steps"])
            self._hyst_left = int(scaler["hyst_left"])
            self.skipped_steps = int(scaler.get("skipped", 0))
        for gi, flat in enumerate(sd["master"]):
            self.master[gi] = [np.ascontiguousarray(f, np.float32)
                               for f in flat]
            self.host_bf16[gi] = [
                self._host_compute(f, s)
                for f, s in zip(self.master[gi], self.shapes[gi])]
        self.other_master = [np.ascontiguousarray(f, np.float32)
                             for f in sd["other_master"]]
        self.other_dev = self._other_to_device()
        # moment entries come in two layouts — per-leaf keys "G{gi}.{j}"
        # (host tier) or concatenated-per-group keys "G{gi}" (NVMe tier).
        # Translate whichever we get into THIS engine's tier so cross-tier
        # restores keep their moments instead of silently resetting.
        concat: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for key, st in sd.get("adam", {}).items():
            m = np.ascontiguousarray(st["m"], np.float32)
            v = np.ascontiguousarray(st["v"], np.float32)
            if key.startswith("G") and "." not in key:
                concat[int(key[1:])] = (m, v)
            elif key.startswith("G") and self.swapper is not None:
                gi, j = (int(x) for x in key[1:].split("."))
                cm, cv = concat.setdefault(gi, (
                    np.zeros(sum(f.size for f in self.master[gi]),
                             np.float32),
                    np.zeros(sum(f.size for f in self.master[gi]),
                             np.float32)))
                off = sum(f.size for f in self.master[gi][:j])
                cm[off:off + m.size] = m
                cv[off:off + v.size] = v
            else:
                self.adam.load_state(key, self.step_count, m, v)
        for gi, (cm, cv) in concat.items():
            if self.swapper is not None:
                self.swapper.swap_out(f"G{gi}", [cm, cv])
            else:
                off = 0
                for j, f in enumerate(self.master[gi]):
                    self.adam.load_state(f"G{gi}.{j}", self.step_count,
                                         cm[off:off + f.size],
                                         cv[off:off + f.size])
                    off += f.size
