"""Sharded checkpoint save/load with `latest`-tag semantics.

TPU-native analog of the reference checkpoint layer
(ref: deepspeed/runtime/engine.py:2739 save_checkpoint, :2414
load_checkpoint, `latest` tag file :2919, tag validation :2721). The
reference writes per-rank torch files (mp_rank_XX_model_states.pt +
zero_pp_rank_X_..._optim_states.pt); here orbax/tensorstore writes ONE
logical sharded checkpoint that any device count can reload — which also
subsumes the reference's "elastic checkpoint" DP-degree resharding
(stage_1_and_2.py:2002) and the offline zero_to_fp32.py consolidation
script: ``load_fp32_state_dict_from_zero_checkpoint`` below restores full
fp32 weights on host from the sharded files.
"""

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
META_FILE = "ds_meta.json"
STATE_DIR = "state"


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.expanduser(save_dir), str(tag))


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None,
                    save_latest: bool = True) -> bool:
    """Write the engine state (params, optimizer, loss-scale, counters)."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    tag = str(tag)
    path = _tag_dir(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    state = engine.state
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "scale_state": state.scale_state._asdict(),
        "rng": state.rng,
    }
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, STATE_DIR), payload, force=True)
    ckptr.wait_until_finished()

    # host-resident optimizer state (ZeRO-Offload): fp32 masters + moments
    # (analog of the per-DP-rank optim_states.pt shards, engine.py:2327)
    if getattr(engine, "offload_enabled", False):
        if jax.process_count() > 1:
            # per-process shard-piece files (the analog of the reference's
            # per-DP-rank zero_pp_rank_X_..._optim_states.pt shards,
            # engine.py:2327): each process saves exactly the regions it
            # addresses; load merges every process's pieces, so restores
            # work at ANY process count / shard layout.
            pieces = engine.host_optimizer.shard_export()
            arrays = {"step": np.asarray(
                engine.host_optimizer.step_count),
                "n_pieces": np.asarray(len(pieces))}
            for n_, p in enumerate(pieces):
                for field in ("leaf", "starts", "stops", "master",
                              "exp_avg", "exp_avg_sq"):
                    arrays[f"piece{n_}_{field}"] = p[field]
            np.savez(os.path.join(
                path, f"host_optim_states_p{jax.process_index()}.npz"),
                **arrays)
        else:
            # single host: one consolidated global file
            sd = engine.host_optimizer.state_dict()
            arrays = {"step": np.asarray(sd["step"])}
            for i, m in enumerate(sd["master"]):
                arrays[f"master_{i}"] = m
            for key, st in sd["state"].items():
                arrays[f"exp_avg_{key}"] = st["exp_avg"]
                arrays[f"exp_avg_sq_{key}"] = st["exp_avg_sq"]
            np.savez(os.path.join(path, "host_optim_states.npz"), **arrays)

    meta = {
        "tag": tag,
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.config.zero.stage,
        "precision": engine.config.precision_name,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, META_FILE), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if save_latest:
            with open(os.path.join(os.path.expanduser(save_dir), LATEST_FILE), "w") as f:
                f.write(tag)
    log_dist(f"saved checkpoint {tag} to {path}", ranks=[0])
    return True


def get_latest_tag(load_dir: str) -> Optional[str]:
    latest_path = os.path.join(os.path.expanduser(load_dir), LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True):
    """Restore engine state; resharding to the current mesh is automatic
    (elastic checkpoint — any dp/tp degree can load any other's save)."""
    if tag is None:
        tag = get_latest_tag(load_dir)
        if tag is None:
            logger.warning(
                f"Unable to find latest file at {load_dir}/{LATEST_FILE}, "
                "if trying to load latest checkpoint please pass a valid tag")
            return None, {}
    path = _tag_dir(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"checkpoint dir {path} does not exist")
        return None, {}

    state = engine.state
    sh = engine._state_shardings

    def abstract(x, s):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    target = {
        "step": abstract(state.step, sh.step),
        "params": jax.tree_util.tree_map(abstract, state.params, sh.params),
        "opt_state": jax.tree_util.tree_map(abstract, state.opt_state, sh.opt_state),
        "scale_state": {k: abstract(v, s) for (k, v), s in
                        zip(state.scale_state._asdict().items(),
                            sh.scale_state)},
        "rng": abstract(state.rng, sh.rng),
    }
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(path, STATE_DIR), target)

    from deepspeed_tpu.runtime.loss_scaler import LossScaleState
    scale_state = LossScaleState(**restored["scale_state"])
    opt_state = restored["opt_state"] if load_optimizer_states else state.opt_state

    if getattr(engine, "offload_enabled", False):
        import glob as _glob
        piece_files = sorted(_glob.glob(
            os.path.join(path, "host_optim_states_p*.npz")))
        host_path = os.path.join(path, "host_optim_states.npz")
        if load_optimizer_states and piece_files:
            # multi-host save: merge every process's shard pieces —
            # restores at any process count / shard layout
            pieces, step = [], 0
            legacy_file = None
            for f in piece_files:
                z = np.load(f)
                if "n_pieces" not in z:
                    # pre-shard-piece per-process file (consolidated
                    # global arrays): only valid for this process's own
                    # shard layout — handled below
                    if f.endswith(f"_p{jax.process_index()}.npz"):
                        legacy_file = f
                    continue
                step = int(z["step"])
                for n_ in range(int(z["n_pieces"])):
                    pieces.append({
                        field: z[f"piece{n_}_{field}"]
                        for field in ("leaf", "starts", "stops", "master",
                                      "exp_avg", "exp_avg_sq")})
            if pieces:
                engine.host_optimizer.shard_import(pieces, step)
            elif legacy_file is not None:
                z = np.load(legacy_file)
                n = len(engine.host_optimizer.master)
                engine.host_optimizer.load_state_dict({
                    "step": int(z["step"]),
                    "master": [z[f"master_{i}"] for i in range(n)],
                    "state": {str(i): {"exp_avg": z[f"exp_avg_{i}"],
                                       "exp_avg_sq": z[f"exp_avg_sq_{i}"]}
                              for i in range(n)},
                })
            else:
                logger.warning(
                    "offload engine: no readable host state pieces; "
                    "reinitializing masters from restored params")
                engine.host_optimizer.reset_from_params(restored["params"])
        elif load_optimizer_states and os.path.isfile(host_path):
            z = np.load(host_path)
            n = len(engine.host_optimizer.master)
            engine.host_optimizer.load_state_dict({
                "step": int(z["step"]),
                "master": [z[f"master_{i}"] for i in range(n)],
                "state": {str(i): {"exp_avg": z[f"exp_avg_{i}"],
                                   "exp_avg_sq": z[f"exp_avg_sq_{i}"]}
                          for i in range(n)},
            })
        else:
            # no host state on disk (non-offload save, or optimizer states
            # skipped): re-seed the host fp32 masters from the restored
            # device params so the next step doesn't revert to init weights
            if load_optimizer_states:
                logger.warning(
                    "offload engine: %s missing; reinitializing host "
                    "optimizer masters from restored params (moments reset)",
                    host_path)
            engine.host_optimizer.reset_from_params(restored["params"])

    from deepspeed_tpu.runtime.engine import TrainState
    engine.state = TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=opt_state,
        scale_state=scale_state,
        rng=restored["rng"],
        # compressed-comm error residuals restart at zero after resume
        # (same as the reference's worker_error, re-allocated at init)
        comm_error=(engine._init_comm_error(restored["params"])
                    if getattr(engine, "compressed_comm", False) else None))

    client_state: Dict[str, Any] = {}
    meta_path = os.path.join(path, META_FILE)
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        engine.skipped_steps = meta.get("skipped_steps", 0)
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {tag} from {path}", ranks=[0])
    return path, client_state


# ---------------------------------------------------------------------------
# consolidation tooling (zero_to_fp32 analog, ref: deepspeed/utils/zero_to_fp32.py)
# ---------------------------------------------------------------------------

def load_fp32_state_dict_from_zero_checkpoint(ckpt_dir: str,
                                              tag: Optional[str] = None):
    """Rebuild the full fp32 param pytree on host from a sharded checkpoint,
    without an engine (offline tool parity with zero_to_fp32.py)."""
    if tag is None:
        tag = get_latest_tag(ckpt_dir)
        assert tag is not None, f"no latest tag in {ckpt_dir}"
    path = os.path.join(_tag_dir(ckpt_dir, tag), STATE_DIR)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path)
    params = restored["params"]
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, dtype=np.float32), params)


def get_fp32_state_dict_from_zero_checkpoint(ckpt_dir: str,
                                             tag: Optional[str] = None):
    return load_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)


# ---------------------------------------------------------------------------
# flat 16-bit weight export (ref: engine.py:3136 save_16bit_model)
# ---------------------------------------------------------------------------

def _flat_key(path) -> str:
    import jax
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def write_16bit_model(params, save_dir: str,
                      save_filename: str = "model_weights.npz") -> str:
    """Save a param pytree as one flat npz with path-joined keys.
    bf16 (npz-unrepresentable) leaves are stored as uint16 bit patterns;
    a ``__bf16_keys__`` manifest records which, so load_16bit_model can
    reverse the view exactly."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    os.makedirs(save_dir, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out, bf16_keys = {}, []
    for path, leaf in flat:
        k = _flat_key(path)
        a = np.asarray(leaf)
        if a.dtype == jnp.bfloat16.dtype:
            bf16_keys.append(k)
            a = a.view(np.uint16)
        out[k] = a
    out["__bf16_keys__"] = np.asarray(bf16_keys, dtype="U")
    path = os.path.join(save_dir, save_filename)
    np.savez(path, **out)
    return path


def load_16bit_model(path: str):
    """Inverse of write_16bit_model: returns a NESTED dict pytree
    (splitting keys on '/') with bf16 leaves restored."""
    import jax.numpy as jnp
    import numpy as np

    with np.load(path) as z:
        bf16 = set(z["__bf16_keys__"].tolist())
        tree = {}
        for k in z.files:
            if k == "__bf16_keys__":
                continue
            a = z[k]
            if k in bf16:
                a = a.view(jnp.bfloat16.dtype)
            node = tree
            parts = k.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = a
    return tree
