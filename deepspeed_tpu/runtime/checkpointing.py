"""Sharded checkpoint save/load with `latest`-tag semantics.

TPU-native analog of the reference checkpoint layer
(ref: deepspeed/runtime/engine.py:2739 save_checkpoint, :2414
load_checkpoint, `latest` tag file :2919, tag validation :2721). The
reference writes per-rank torch files (mp_rank_XX_model_states.pt +
zero_pp_rank_X_..._optim_states.pt); here orbax/tensorstore writes ONE
logical sharded checkpoint that any device count can reload — which also
subsumes the reference's "elastic checkpoint" DP-degree resharding
(stage_1_and_2.py:2002) and the offline zero_to_fp32.py consolidation
script: ``load_fp32_state_dict_from_zero_checkpoint`` below restores full
fp32 weights on host from the sharded files.

Crash consistency (docs/ROBUSTNESS.md):

- single-process saves STAGE the whole tag under ``<tag>.building`` and
  commit it with one directory rename — a crash anywhere before the
  commit leaves no visible tag, so readers never see a half-written
  checkpoint; multi-process saves write in place (a cross-process
  staged rename would need a barrier this layer doesn't own) and rely
  on the pointer commit below;
- the ``latest`` pointer is replaced atomically (tmp file + fsync +
  ``os.replace`` + directory fsync) — the commit point: until it lands,
  every loader still resolves the previous checkpoint;
- every tag carries ``ds_manifest.json`` (per-file byte size + crc32);
  :func:`validate_tag` rejects torn or bit-rotted tags, and
  :func:`load_checkpoint` walks back from an invalid ``latest`` to the
  newest valid tag (``strict=True`` raises instead);
- the ``checkpoint.pre_commit`` / ``checkpoint.commit`` fault-injection
  sites (utils/faults) simulate a crash just before / just after the
  tag commit, which is how tests/test_checkpointing.py drives both
  recovery paths.
"""

import json
import os
import shutil
import zlib
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.faults import maybe_fire
from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
META_FILE = "ds_meta.json"
MANIFEST_FILE = "ds_manifest.json"
STATE_DIR = "state"
_BUILD_SUFFIX = ".building"   # staged (uncommitted) tag dir
_OLD_SUFFIX = ".old"          # displaced previous tag during overwrite


class CheckpointError(RuntimeError):
    """No loadable checkpoint (missing/corrupt tag with ``strict=True``)."""


def _tag_dir(save_dir: str, tag: str) -> str:
    # abspath because orbax/tensorstore refuses relative checkpoint
    # paths ("Checkpoint path should be absolute") and the error only
    # surfaces from the async commit thread
    return os.path.join(_root(save_dir), str(tag))


def _root(save_dir: str) -> str:
    return os.path.abspath(os.path.expanduser(save_dir))


def _fsync_dir(path: str) -> None:
    """Persist a directory entry (the rename itself) to disk."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return   # platform/filesystem without directory open support
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_text(path: str, text: str) -> None:
    """Pointer-file replacement that is atomic AND durable: readers see
    either the old or the new content, never a torn write, even across
    a crash (tmp + fsync + rename + parent fsync)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _write_manifest(tag_path: str, tag: str) -> None:
    """Record every payload file's size + crc32 so a partial write or
    bit rot is detectable at load time (validate_tag)."""
    files: Dict[str, Dict[str, int]] = {}
    for root, _dirs, names in os.walk(tag_path):
        for name in names:
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, tag_path)
            if rel == MANIFEST_FILE:
                continue
            files[rel] = {"bytes": os.path.getsize(fp),
                          "crc32": _file_crc32(fp)}
    _atomic_write_text(os.path.join(tag_path, MANIFEST_FILE),
                       json.dumps({"tag": tag, "files": files}, indent=1,
                                  sort_keys=True))


def validate_tag(load_dir: str, tag: str) -> bool:
    """True when the tag directory exists and every manifest-listed file
    matches its recorded size and crc32. Pre-manifest (legacy) tags
    validate on the presence of the state dir."""
    path = _tag_dir(load_dir, str(tag))
    if not os.path.isdir(path):
        return False
    man = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(man):
        return os.path.isdir(os.path.join(path, STATE_DIR))
    try:
        with open(man) as f:
            entries = json.load(f)["files"]
    except (OSError, ValueError, KeyError):
        return False
    for rel, info in entries.items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            return False
        if os.path.getsize(fp) != info.get("bytes"):
            return False
        if _file_crc32(fp) != info.get("crc32"):
            return False
    return True


def list_tags(load_dir: str) -> List[str]:
    """Candidate tag directories under ``load_dir``, newest first
    (directory mtime). Staged ``.building`` and displaced ``.old`` dirs
    are never candidates."""
    root = _root(load_dir)
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        p = os.path.join(root, name)
        if not os.path.isdir(p) or name.startswith(".") \
                or name.endswith(_BUILD_SUFFIX) or name.endswith(_OLD_SUFFIX):
            continue
        out.append((os.path.getmtime(p), name))
    return [name for _mt, name in sorted(out, reverse=True)]


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None,
                    save_latest: bool = True) -> bool:
    """Write the engine state (params, optimizer, loss-scale, counters).

    Single-process saves are crash-atomic: the tag is staged under
    ``<tag>.building`` and committed with one rename; ``latest`` is
    replaced atomically afterwards. A crash at ANY point leaves the
    previous checkpoint fully loadable."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    tag = str(tag)
    save_root = _root(save_dir)
    final_path = _tag_dir(save_dir, tag)
    staged = jax.process_count() == 1
    path = final_path + _BUILD_SUFFIX if staged else final_path
    if staged and os.path.exists(path):
        shutil.rmtree(path)   # leftover from a previous crashed save
    os.makedirs(path, exist_ok=True)

    state = engine.state
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "scale_state": state.scale_state._asdict(),
        "rng": state.rng,
    }
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, STATE_DIR), payload, force=True)
    ckptr.wait_until_finished()

    # host-resident optimizer state (ZeRO-Offload): fp32 masters + moments
    # (analog of the per-DP-rank optim_states.pt shards, engine.py:2327)
    if getattr(engine, "offload_enabled", False):
        if jax.process_count() > 1:
            # per-process shard-piece files (the analog of the reference's
            # per-DP-rank zero_pp_rank_X_..._optim_states.pt shards,
            # engine.py:2327): each process saves exactly the regions it
            # addresses; load merges every process's pieces, so restores
            # work at ANY process count / shard layout.
            pieces = engine.host_optimizer.shard_export()
            arrays = {"step": np.asarray(
                engine.host_optimizer.step_count),
                "n_pieces": np.asarray(len(pieces))}
            for n_, p in enumerate(pieces):
                for field in ("leaf", "starts", "stops", "master",
                              "exp_avg", "exp_avg_sq"):
                    arrays[f"piece{n_}_{field}"] = p[field]
            np.savez(os.path.join(
                path, f"host_optim_states_p{jax.process_index()}.npz"),
                **arrays)
        else:
            # single host: one consolidated global file
            sd = engine.host_optimizer.state_dict()
            arrays = {"step": np.asarray(sd["step"])}
            for i, m in enumerate(sd["master"]):
                arrays[f"master_{i}"] = m
            for key, st in sd["state"].items():
                arrays[f"exp_avg_{key}"] = st["exp_avg"]
                arrays[f"exp_avg_sq_{key}"] = st["exp_avg_sq"]
            np.savez(os.path.join(path, "host_optim_states.npz"), **arrays)

    meta = {
        "tag": tag,
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.config.zero.stage,
        "precision": engine.config.precision_name,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    if jax.process_index() == 0:
        _atomic_write_text(os.path.join(path, META_FILE),
                           json.dumps(meta, indent=2, default=str))
        # manifest LAST: it attests every payload file above (in a
        # multi-process save it covers the files visible to process 0)
        _write_manifest(path, tag)
    # crash here (pre-commit): the staged dir is invisible to loaders
    maybe_fire("checkpoint.pre_commit")
    if staged:
        displaced = None
        if os.path.exists(final_path):
            # a dir rename cannot atomically replace a non-empty dst:
            # displace the old tag aside first (an interrupted save
            # leaves either old-aside+new or old-in-place — both are
            # valid states for validate_tag/walk-back)
            displaced = final_path + _OLD_SUFFIX
            if os.path.exists(displaced):
                shutil.rmtree(displaced)
            os.rename(final_path, displaced)
        os.rename(path, final_path)
        _fsync_dir(save_root)
        if displaced is not None:
            shutil.rmtree(displaced)
    # crash here (post-commit): the tag is durable but `latest` still
    # points at the previous one — exactly the walk-forwardable state
    # the crash-recovery test pins
    maybe_fire("checkpoint.commit")
    if jax.process_index() == 0 and save_latest:
        _atomic_write_text(os.path.join(save_root, LATEST_FILE), tag)
    log_dist(f"saved checkpoint {tag} to {final_path}", ranks=[0])
    return True


def get_latest_tag(load_dir: str) -> Optional[str]:
    latest_path = os.path.join(_root(load_dir), LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    strict: bool = False):
    """Restore engine state; resharding to the current mesh is automatic
    (elastic checkpoint — any dp/tp degree can load any other's save).

    Corruption handling: every tag is manifest-validated before restore.
    When the implicit ``latest`` tag is missing or fails validation
    (torn write, crash mid-save, bit rot), the loader WALKS BACK to the
    newest valid tag in ``load_dir``. An explicitly requested ``tag``
    is never silently substituted. ``strict=True`` raises
    :class:`CheckpointError` instead of warn-and-return-``(None, {})``."""
    requested = tag
    if tag is None:
        tag = get_latest_tag(load_dir)
        if tag is None:
            msg = (f"Unable to find latest file at {load_dir}/{LATEST_FILE},"
                   " if trying to load latest checkpoint please pass a valid"
                   " tag")
            if strict:
                raise CheckpointError(msg)
            logger.warning(msg)
            return None, {}
    if not validate_tag(load_dir, tag):
        if requested is not None:
            msg = (f"checkpoint {tag} at {load_dir} is missing or fails "
                   f"manifest validation")
            if strict:
                raise CheckpointError(msg)
            logger.warning(msg)
            return None, {}
        fallback = next((t for t in list_tags(load_dir)
                         if t != tag and validate_tag(load_dir, t)), None)
        if fallback is None:
            msg = (f"latest checkpoint {tag} at {load_dir} is invalid and "
                   f"no valid tag remains")
            if strict:
                raise CheckpointError(msg)
            logger.warning(msg)
            return None, {}
        logger.warning(
            f"latest checkpoint {tag} at {load_dir} is missing or corrupt; "
            f"walking back to newest valid tag {fallback}")
        tag = fallback
    path = _tag_dir(load_dir, tag)

    state = engine.state
    sh = engine._state_shardings

    def abstract(x, s):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    target = {
        "step": abstract(state.step, sh.step),
        "params": jax.tree_util.tree_map(abstract, state.params, sh.params),
        "opt_state": jax.tree_util.tree_map(abstract, state.opt_state, sh.opt_state),
        "scale_state": {k: abstract(v, s) for (k, v), s in
                        zip(state.scale_state._asdict().items(),
                            sh.scale_state)},
        "rng": abstract(state.rng, sh.rng),
    }
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(path, STATE_DIR), target)

    from deepspeed_tpu.runtime.loss_scaler import LossScaleState
    scale_state = LossScaleState(**restored["scale_state"])
    opt_state = restored["opt_state"] if load_optimizer_states else state.opt_state

    if getattr(engine, "offload_enabled", False):
        import glob as _glob
        piece_files = sorted(_glob.glob(
            os.path.join(path, "host_optim_states_p*.npz")))
        host_path = os.path.join(path, "host_optim_states.npz")
        if load_optimizer_states and piece_files:
            # multi-host save: merge every process's shard pieces —
            # restores at any process count / shard layout
            pieces, step = [], 0
            legacy_file = None
            for f in piece_files:
                z = np.load(f)
                if "n_pieces" not in z:
                    # pre-shard-piece per-process file (consolidated
                    # global arrays): only valid for this process's own
                    # shard layout — handled below
                    if f.endswith(f"_p{jax.process_index()}.npz"):
                        legacy_file = f
                    continue
                step = int(z["step"])
                for n_ in range(int(z["n_pieces"])):
                    pieces.append({
                        field: z[f"piece{n_}_{field}"]
                        for field in ("leaf", "starts", "stops", "master",
                                      "exp_avg", "exp_avg_sq")})
            if pieces:
                engine.host_optimizer.shard_import(pieces, step)
            elif legacy_file is not None:
                z = np.load(legacy_file)
                n = len(engine.host_optimizer.master)
                engine.host_optimizer.load_state_dict({
                    "step": int(z["step"]),
                    "master": [z[f"master_{i}"] for i in range(n)],
                    "state": {str(i): {"exp_avg": z[f"exp_avg_{i}"],
                                       "exp_avg_sq": z[f"exp_avg_sq_{i}"]}
                              for i in range(n)},
                })
            else:
                logger.warning(
                    "offload engine: no readable host state pieces; "
                    "reinitializing masters from restored params")
                engine.host_optimizer.reset_from_params(restored["params"])
        elif load_optimizer_states and os.path.isfile(host_path):
            z = np.load(host_path)
            n = len(engine.host_optimizer.master)
            engine.host_optimizer.load_state_dict({
                "step": int(z["step"]),
                "master": [z[f"master_{i}"] for i in range(n)],
                "state": {str(i): {"exp_avg": z[f"exp_avg_{i}"],
                                   "exp_avg_sq": z[f"exp_avg_sq_{i}"]}
                          for i in range(n)},
            })
        else:
            # no host state on disk (non-offload save, or optimizer states
            # skipped): re-seed the host fp32 masters from the restored
            # device params so the next step doesn't revert to init weights
            if load_optimizer_states:
                logger.warning(
                    "offload engine: %s missing; reinitializing host "
                    "optimizer masters from restored params (moments reset)",
                    host_path)
            engine.host_optimizer.reset_from_params(restored["params"])

    from deepspeed_tpu.runtime.engine import TrainState
    engine.state = TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=opt_state,
        scale_state=scale_state,
        rng=restored["rng"],
        # compressed-comm error residuals restart at zero after resume
        # (same as the reference's worker_error, re-allocated at init)
        comm_error=(engine._init_comm_error(restored["params"])
                    if getattr(engine, "compressed_comm", False) else None))

    client_state: Dict[str, Any] = {}
    meta_path = os.path.join(path, META_FILE)
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        engine.skipped_steps = meta.get("skipped_steps", 0)
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {tag} from {path}", ranks=[0])
    return path, client_state


# ---------------------------------------------------------------------------
# consolidation tooling (zero_to_fp32 analog, ref: deepspeed/utils/zero_to_fp32.py)
# ---------------------------------------------------------------------------

def load_fp32_state_dict_from_zero_checkpoint(ckpt_dir: str,
                                              tag: Optional[str] = None):
    """Rebuild the full fp32 param pytree on host from a sharded checkpoint,
    without an engine (offline tool parity with zero_to_fp32.py)."""
    if tag is None:
        tag = get_latest_tag(ckpt_dir)
        assert tag is not None, f"no latest tag in {ckpt_dir}"
    path = os.path.join(_tag_dir(ckpt_dir, tag), STATE_DIR)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path)
    params = restored["params"]
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, dtype=np.float32), params)


def get_fp32_state_dict_from_zero_checkpoint(ckpt_dir: str,
                                             tag: Optional[str] = None):
    return load_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)


# ---------------------------------------------------------------------------
# flat 16-bit weight export (ref: engine.py:3136 save_16bit_model)
# ---------------------------------------------------------------------------

def _flat_key(path) -> str:
    import jax
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def write_16bit_model(params, save_dir: str,
                      save_filename: str = "model_weights.npz") -> str:
    """Save a param pytree as one flat npz with path-joined keys.
    bf16 (npz-unrepresentable) leaves are stored as uint16 bit patterns;
    a ``__bf16_keys__`` manifest records which, so load_16bit_model can
    reverse the view exactly."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    os.makedirs(save_dir, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out, bf16_keys = {}, []
    for path, leaf in flat:
        k = _flat_key(path)
        a = np.asarray(leaf)
        if a.dtype == jnp.bfloat16.dtype:
            bf16_keys.append(k)
            a = a.view(np.uint16)
        out[k] = a
    out["__bf16_keys__"] = np.asarray(bf16_keys, dtype="U")
    path = os.path.join(save_dir, save_filename)
    np.savez(path, **out)
    return path


def load_16bit_model(path: str):
    """Inverse of write_16bit_model: returns a NESTED dict pytree
    (splitting keys on '/') with bf16 leaves restored."""
    import jax.numpy as jnp
    import numpy as np

    with np.load(path) as z:
        bf16 = set(z["__bf16_keys__"].tolist())
        tree = {}
        for k in z.files:
            if k == "__bf16_keys__":
                continue
            a = z[k]
            if k in bf16:
                a = a.view(jnp.bfloat16.dtype)
            node = tree
            parts = k.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = a
    return tree
