"""Data loading helpers.

TPU-native analog of deepspeed/runtime/dataloader.py (DeepSpeedDataLoader +
RepeatingLoader). There is no torch DataLoader/DistributedSampler here: in
single-controller JAX every process feeds the GLOBAL batch (sharded arrays),
so the loader yields numpy batches of the full train_batch_size; the engine's
input sharding scatters them over the mesh.
"""

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class DeepSpeedDataLoader:
    """Batches an indexable dataset of pytrees into stacked numpy arrays."""

    def __init__(self, dataset: Sequence, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self.len = len(dataset) // batch_size if drop_last else \
            (len(dataset) + batch_size - 1) // batch_size

    def __len__(self):
        return self.len

    def __iter__(self) -> Iterator[Any]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        for i in range(self.len):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(j)] for j in idx])


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([it[i] for it in items])
                           for i in range(len(first)))
    return np.stack(items)


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration
    (ref: dataloader.py RepeatingLoader)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class PrefetchLoader:
    """Device-prefetching wrapper: places batch N+1 on the mesh while the
    step consuming batch N is still running.

    The reference overlaps H2D with compute via pinned-memory CUDA streams
    inside torch's DataLoader; the TPU equivalent is simply issuing the
    (async) ``jax.device_put`` one batch ahead — dispatch returns
    immediately and the transfer rides behind the running step. The engine
    detects pre-placed batches in ``_shard_batch`` (already-committed
    arrays pass through ``jax.device_put`` unchanged).

    Usage::

        loader = PrefetchLoader(loader, engine)
        for batch in loader:
            engine.train_batch(batch)
    """

    def __init__(self, loader: Iterable, engine, depth: int = 1):
        assert depth >= 1
        self.loader = loader
        self.engine = engine
        self.depth = depth

    def __iter__(self):
        import collections
        q = collections.deque()
        it = iter(self.loader)
        try:
            while len(q) < self.depth:
                q.append(self.engine._shard_batch(next(it)))
        except StopIteration:
            pass
        while q:
            try:
                q.append(self.engine._shard_batch(next(it)))
            except StopIteration:
                pass
            yield q.popleft()


def pack_documents(docs, seq_len: int, pad_token: int = 0):
    """Greedy first-fit packing of token sequences into fixed-length rows.

    Produces the packed-batch dict the GPT loss understands:
    ``{"tokens", "segment_ids", "positions", "loss_mask"}`` — attention
    stays block-diagonal per document (flash segment_ids path), positions
    restart at each document, and the loss mask zeroes both padding and
    each document's last token (whose next-token target would cross into
    the following document).

    docs: iterable of 1-D int sequences (len >= 2 each; longer than
    seq_len gets split). Returns numpy arrays with leading dim = number
    of packed rows.
    """
    rows = []          # all rows: list of [(doc, len), ...]
    open_rows = []     # (used, row) candidates with remaining space
    for doc in docs:
        doc = np.asarray(doc, np.int32)
        while len(doc) > seq_len:
            head, doc = doc[:seq_len], doc[seq_len:]
            rows.append([(head, len(head))])   # full — never a candidate
            if len(doc) < 2:
                break
        if len(doc) < 2:
            continue
        for slot in open_rows:
            if slot[0] + len(doc) <= seq_len:
                slot[1].append((doc, len(doc)))
                slot[0] += len(doc)
                if slot[0] > seq_len - 2:      # nothing (len>=2) fits now
                    open_rows.remove(slot)
                break
        else:
            row = [(doc, len(doc))]
            rows.append(row)
            if len(doc) <= seq_len - 2:
                open_rows.append([len(doc), row])

    n = len(rows)
    tokens = np.full((n, seq_len), pad_token, np.int32)
    segs = np.full((n, seq_len), -1, np.int32)   # -1 = padding segment
    poss = np.zeros((n, seq_len), np.int32)
    mask = np.zeros((n, seq_len - 1), np.float32)
    for i, row in enumerate(rows):
        off = 0
        for sid, (doc, ln) in enumerate(row):
            tokens[i, off:off + ln] = doc
            segs[i, off:off + ln] = sid
            poss[i, off:off + ln] = np.arange(ln)
            # predictable targets: positions off..off+ln-2 (within-doc)
            mask[i, off:off + ln - 1] = 1.0
            off += ln
    return {"tokens": tokens, "segment_ids": segs, "positions": poss,
            "loss_mask": mask}


def zigzag_batch(batch, n_seq: int):
    """Re-lay a next-token batch for the zigzag ring layout
    (ops/attention/ring.py ``zigzag_perm``): derive the (inputs,
    targets) pair FIRST, then apply the same permutation to inputs,
    targets, and every piece of per-token metadata — permuting the raw
    [B, S+1] token row would not commute with next-token slicing.

    batch: {"tokens": [B, S+1]} optionally with "segment_ids"/
    "positions" [B, S+1] and "loss_mask" [B, S] (pack_documents
    layout). Returns the explicit-targets dict the GPT loss consumes,
    with "positions" always present (the model's positional encodings
    must follow their tokens; for unpacked batches that is the
    permutation itself).
    """
    from deepspeed_tpu.ops.attention.ring import zigzag_perm
    toks = np.asarray(batch["tokens"])
    B, S = toks.shape[0], toks.shape[1] - 1
    p = zigzag_perm(S, n_seq)
    out = {"tokens": toks[:, :-1][:, p], "targets": toks[:, 1:][:, p]}
    poss = batch.get("positions")
    out["positions"] = (np.asarray(poss)[:, :-1][:, p]
                        if poss is not None
                        else np.broadcast_to(p.astype(np.int32), (B, S)))
    segs = batch.get("segment_ids")
    if segs is not None:
        out["segment_ids"] = np.asarray(segs)[:, :-1][:, p]
    mask = batch.get("loss_mask")
    if mask is not None:
        assert mask.shape[-1] == S, (mask.shape, S)
        out["loss_mask"] = np.asarray(mask)[:, p]
    return out
