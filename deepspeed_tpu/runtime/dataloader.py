"""Data loading helpers.

TPU-native analog of deepspeed/runtime/dataloader.py (DeepSpeedDataLoader +
RepeatingLoader). There is no torch DataLoader/DistributedSampler here: in
single-controller JAX every process feeds the GLOBAL batch (sharded arrays),
so the loader yields numpy batches of the full train_batch_size; the engine's
input sharding scatters them over the mesh.
"""

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class DeepSpeedDataLoader:
    """Batches an indexable dataset of pytrees into stacked numpy arrays."""

    def __init__(self, dataset: Sequence, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self.len = len(dataset) // batch_size if drop_last else \
            (len(dataset) + batch_size - 1) // batch_size

    def __len__(self):
        return self.len

    def __iter__(self) -> Iterator[Any]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        for i in range(self.len):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(j)] for j in idx])


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([it[i] for it in items])
                           for i in range(len(first)))
    return np.stack(items)


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration
    (ref: dataloader.py RepeatingLoader)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class PrefetchLoader:
    """Device-prefetching wrapper: places batch N+1 on the mesh while the
    step consuming batch N is still running.

    The reference overlaps H2D with compute via pinned-memory CUDA streams
    inside torch's DataLoader; the TPU equivalent is simply issuing the
    (async) ``jax.device_put`` one batch ahead — dispatch returns
    immediately and the transfer rides behind the running step. The engine
    detects pre-placed batches in ``_shard_batch`` (already-committed
    arrays pass through ``jax.device_put`` unchanged).

    Usage::

        loader = PrefetchLoader(loader, engine)
        for batch in loader:
            engine.train_batch(batch)
    """

    def __init__(self, loader: Iterable, engine, depth: int = 1):
        assert depth >= 1
        self.loader = loader
        self.engine = engine
        self.depth = depth

    def __iter__(self):
        import collections
        q = collections.deque()
        it = iter(self.loader)
        try:
            while len(q) < self.depth:
                q.append(self.engine._shard_batch(next(it)))
        except StopIteration:
            pass
        while q:
            try:
                q.append(self.engine._shard_batch(next(it)))
            except StopIteration:
                pass
            yield q.popleft()
