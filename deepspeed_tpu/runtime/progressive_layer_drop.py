"""Progressive Layer Dropping (PLD) — arXiv:2010.13369.

Capability match for the reference's ``ProgressiveLayerDrop``
(ref: deepspeed/runtime/progressive_layer_drop.py:5): a global keep
probability ``theta(t) = (1-theta)*exp(-gamma*t) + theta`` that decays
from 1.0 toward ``theta``; deeper layers are dropped more aggressively
(the model applies keep prob ``1 - l/L * (1-theta(t))`` per layer).

TPU-native: theta is a deterministic function of the step counter, so
instead of injecting a host-side kwarg each step (ref: engine.py:1542
fwd-kwarg injection, which would force a recompile per value) the
engine computes it *inside* the jitted step from ``state.step`` via
:func:`theta_schedule` and threads it through the batch dict as a
traced scalar under the key ``"pld_theta"``. Models that support PLD
read that key (see models/gpt.py).
"""

import math

import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist

PLD_THETA_KEY = "pld_theta"


def theta_schedule(global_step, theta: float, gamma: float):
    """Pure/traceable: theta(t) = (1-p)*exp(-gamma*t) + p
    (ref: progressive_layer_drop.py:31 _prob)."""
    return (1.0 - theta) * jnp.exp(-gamma * global_step.astype(jnp.float32)) \
        + theta


class ProgressiveLayerDrop:
    """Host-side mirror of the schedule, for reporting/checkpointing
    (the in-jit path uses :func:`theta_schedule` directly)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        self.current_theta = (1.0 - self.theta) * \
            math.exp(-self.gamma * global_step) + self.theta
