"""JSON config file/dict -> typed configuration object.

TPU-native analog of the reference config system
(ref: deepspeed/runtime/config.py:791 DeepSpeedConfig; per-feature getters at
:79-662; zero config at deepspeed/runtime/zero/config.py; offload config at
deepspeed/runtime/zero/offload_config.py). Same JSON schema where it makes
sense on TPU (so a DeepSpeed user's ds_config.json mostly "just works"), plus
a ``mesh`` section describing the named-axis device mesh that replaces
process groups.
"""

import json
import os
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional, Union

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    dict_raise_error_on_duplicate_keys,
    get_scalar_param,
)
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


@dataclass
class FP16Config:
    enabled: bool = False
    loss_scale: float = 0.0          # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0

    @staticmethod
    def from_dict(d: Dict) -> "FP16Config":
        return FP16Config(
            enabled=get_scalar_param(d, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT),
            loss_scale=get_scalar_param(d, C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT),
            initial_scale_power=get_scalar_param(d, C.FP16_INITIAL_SCALE_POWER,
                                                 C.FP16_INITIAL_SCALE_POWER_DEFAULT),
            loss_scale_window=get_scalar_param(d, C.FP16_LOSS_SCALE_WINDOW,
                                               C.FP16_LOSS_SCALE_WINDOW_DEFAULT),
            hysteresis=get_scalar_param(d, C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT),
            min_loss_scale=get_scalar_param(d, C.FP16_MIN_LOSS_SCALE,
                                            C.FP16_MIN_LOSS_SCALE_DEFAULT),
            fp16_master_weights_and_grads=get_scalar_param(
                d, C.FP16_MASTER_WEIGHTS_AND_GRADS,
                C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT),
        )


@dataclass
class BF16Config:
    enabled: bool = False
    # memory-efficient mode: bf16 master weights (stochastic-rounding
    # updates) + bf16 Adam moments — 8 bytes/param of training state
    # instead of 16+. The capability that fits GPT-2 1.5B's full training
    # state in one 16GB chip (the role fp32 masters + offload play in the
    # reference, ref runtime/bf16_optimizer.py:75, at 2x the memory).
    memory_efficient: bool = False

    @staticmethod
    def from_dict(d: Dict) -> "BF16Config":
        return BF16Config(
            enabled=get_scalar_param(d, C.BFLOAT16_ENABLED,
                                     C.BFLOAT16_ENABLED_DEFAULT),
            memory_efficient=get_scalar_param(d, "memory_efficient", False))


@dataclass
class LoraConfig:
    """Config-driven LoRA (runtime/lora.py): the engine adapts the
    param tree and wraps the configured optimizer so only adapter
    leaves train. Beyond the reference surface (v0.6.4 predates LoRA),
    but config-shaped like every other feature."""
    enabled: bool = False
    rank: int = 8
    alpha: float = 16.0
    # dense entries to adapt (missing entries are skipped per-dialect)
    targets: tuple = ("qkv", "attn_out", "mlp_in", "mlp_gate", "mlp_out")
    seed: int = 0

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "LoraConfig":
        if not d:
            return LoraConfig()
        targets = get_scalar_param(
            d, "targets",
            ["qkv", "attn_out", "mlp_in", "mlp_gate", "mlp_out"])
        if isinstance(targets, str):
            # tuple("qkv") would silently become ('q','k','v')
            targets = [targets]
        return LoraConfig(
            enabled=get_scalar_param(d, "enabled", False),
            rank=get_scalar_param(d, "rank", 8),
            alpha=get_scalar_param(d, "alpha", 16.0),
            targets=tuple(targets),
            seed=get_scalar_param(d, "seed", 0))


@dataclass
class OffloadConfig:
    """Offload target for params or optimizer state
    (ref: deepspeed/runtime/zero/offload_config.py)."""
    device: str = C.OFFLOAD_DEVICE_NONE   # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    max_in_cpu: int = 1_000_000_000
    # layers per streamed block for the ZeRO-Infinity param tier
    # (runtime/zero/param_offload.py); 0 = auto-size (<=8 groups,
    # capped block bytes)
    stream_group_layers: int = 0
    # delayed param update: overlap the host optimizer with the NEXT
    # step's device compute at one step of staleness (the ZeRO-Offload
    # paper's DPU mode; bf16 only)
    delayed_param_update: bool = False

    @property
    def enabled(self) -> bool:
        return self.device != C.OFFLOAD_DEVICE_NONE

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "OffloadConfig":
        if not d:
            return OffloadConfig()
        return OffloadConfig(
            device=get_scalar_param(d, C.OFFLOAD_DEVICE, C.OFFLOAD_DEVICE_NONE),
            nvme_path=get_scalar_param(d, C.OFFLOAD_NVME_PATH, None),
            buffer_count=get_scalar_param(d, C.OFFLOAD_BUFFER_COUNT, 5),
            buffer_size=int(get_scalar_param(d, C.OFFLOAD_BUFFER_SIZE, 100_000_000)),
            pin_memory=get_scalar_param(d, C.OFFLOAD_PIN_MEMORY, False),
            pipeline_read=get_scalar_param(d, C.OFFLOAD_PIPELINE_READ, False),
            pipeline_write=get_scalar_param(d, C.OFFLOAD_PIPELINE_WRITE, False),
            max_in_cpu=int(get_scalar_param(d, C.OFFLOAD_MAX_IN_CPU, 1_000_000_000)),
            stream_group_layers=int(get_scalar_param(
                d, "stream_group_layers", 0)),
            delayed_param_update=get_scalar_param(
                d, "delayed_param_update", False),
        )


@dataclass
class ZeroConfig:
    """ZeRO sharding config (ref: deepspeed/runtime/zero/config.py).

    On TPU, stages are realized as sharding specs over the mesh:
      stage 0: everything replicated over 'data'
      stage 1: optimizer state sharded over 'data'
      stage 2: stage 1 + gradients reduce-scattered (XLA emits these when the
               grad accumulator is sharded)
      stage 3: stage 2 + parameters sharded over 'data' (FSDP)
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = False
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    round_robin_gradients: bool = False
    elastic_checkpoint: bool = True
    # minimum trailing-dim size below which a param stays replicated in stage 3
    stage3_min_shard_size: int = 1024

    @property
    def enabled(self) -> bool:
        return self.stage > 0

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "ZeroConfig":
        if not d:
            return ZeroConfig()
        cfg = ZeroConfig(
            stage=get_scalar_param(d, C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT),
            contiguous_gradients=get_scalar_param(d, C.ZERO_CONTIGUOUS_GRADIENTS, True),
            reduce_scatter=get_scalar_param(d, C.ZERO_REDUCE_SCATTER, True),
            reduce_bucket_size=int(get_scalar_param(d, C.ZERO_REDUCE_BUCKET_SIZE, 500_000_000)),
            allgather_partitions=get_scalar_param(d, C.ZERO_ALLGATHER_PARTITIONS, True),
            allgather_bucket_size=int(get_scalar_param(d, C.ZERO_ALLGATHER_BUCKET_SIZE, 500_000_000)),
            overlap_comm=get_scalar_param(d, C.ZERO_OVERLAP_COMM, False),
            offload_param=OffloadConfig.from_dict(d.get(C.ZERO_OFFLOAD_PARAM)),
            offload_optimizer=OffloadConfig.from_dict(d.get(C.ZERO_OFFLOAD_OPTIMIZER)),
            stage3_max_live_parameters=int(get_scalar_param(
                d, C.ZERO_STAGE3_MAX_LIVE_PARAMETERS, 1_000_000_000)),
            stage3_max_reuse_distance=int(get_scalar_param(
                d, C.ZERO_STAGE3_MAX_REUSE_DISTANCE, 1_000_000_000)),
            stage3_prefetch_bucket_size=int(get_scalar_param(
                d, C.ZERO_STAGE3_PREFETCH_BUCKET_SIZE, 50_000_000)),
            stage3_param_persistence_threshold=int(get_scalar_param(
                d, C.ZERO_STAGE3_PARAM_PERSISTENCE_THRESHOLD, 100_000)),
            stage3_gather_16bit_weights_on_model_save=get_scalar_param(
                d, C.ZERO_STAGE3_GATHER_16BIT_WEIGHTS_ON_MODEL_SAVE, False),
            round_robin_gradients=get_scalar_param(d, C.ZERO_ROUND_ROBIN_GRADIENTS, False),
            elastic_checkpoint=get_scalar_param(d, C.ZERO_ELASTIC_CHECKPOINT, True),
            stage3_min_shard_size=int(get_scalar_param(d, "stage3_min_shard_size", 1024)),
        )
        if cfg.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"invalid zero stage {cfg.stage}")
        return cfg


@dataclass
class MeshConfig:
    """Named-axis device mesh replacing the reference's process groups
    (ref: deepspeed/utils/groups.py, deepspeed/runtime/pipe/topology.py).

    The data-parallel degree is derived: dp = world // (tp * pp * sp).
    """
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    sequence_parallel_size: int = 1
    expert_parallel_size: int = 1
    # ZeRO-3 only: number of outer 'data' replicas (the DCN-crossing
    # axis); the remaining dp degree shards params over 'fsdp' inside
    # each replica. 1 = the default all-fsdp layout.
    replica_parallel_size: int = 1

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "MeshConfig":
        if not d:
            return MeshConfig()
        return MeshConfig(
            tensor_parallel_size=get_scalar_param(
                d, C.TENSOR_PARALLEL_SIZE, C.TENSOR_PARALLEL_SIZE_DEFAULT),
            pipeline_parallel_size=get_scalar_param(
                d, C.PIPELINE_PARALLEL_SIZE, C.PIPELINE_PARALLEL_SIZE_DEFAULT),
            sequence_parallel_size=get_scalar_param(
                d, C.SEQUENCE_PARALLEL_SIZE, C.SEQUENCE_PARALLEL_SIZE_DEFAULT),
            expert_parallel_size=get_scalar_param(
                d, C.EXPERT_PARALLEL_SIZE, C.EXPERT_PARALLEL_SIZE_DEFAULT),
            replica_parallel_size=get_scalar_param(
                d, C.REPLICA_PARALLEL_SIZE, C.REPLICA_PARALLEL_SIZE_DEFAULT),
        )


@dataclass
class ActivationCheckpointingConfig:
    """ref: deepspeed/runtime/activation_checkpointing/checkpointing.py config."""
    partition_activations: bool = False
    number_checkpoints: Optional[int] = None
    contiguous_memory_optimization: bool = False
    synchronize_checkpoint_boundary: bool = False
    cpu_checkpointing: bool = False
    profile: bool = False

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "ActivationCheckpointingConfig":
        if not d:
            return ActivationCheckpointingConfig()
        return ActivationCheckpointingConfig(
            partition_activations=get_scalar_param(d, C.ACT_CKPT_PARTITION_ACTIVATIONS, False),
            number_checkpoints=get_scalar_param(d, C.ACT_CKPT_NUMBER_CHECKPOINTS, None),
            contiguous_memory_optimization=get_scalar_param(
                d, C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION, False),
            synchronize_checkpoint_boundary=get_scalar_param(
                d, C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY, False),
            cpu_checkpointing=get_scalar_param(d, C.ACT_CKPT_CPU_CHECKPOINTING, False),
            profile=get_scalar_param(d, C.ACT_CKPT_PROFILE, False),
        )


@dataclass
class SparseAttentionConfig:
    """Block-sparse attention pattern config
    (ref: deepspeed/ops/sparse_attention/sparsity_config.py:9,63,94,243,421,544)."""
    mode: str = C.SPARSE_FIXED_MODE
    block: int = 16
    different_layout_per_head: bool = False
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    attention: str = "bidirectional"
    horizontal_global_attention: bool = False
    num_different_global_patterns: int = 1
    num_random_blocks: int = 0
    local_window_blocks: List[int] = field(default_factory=lambda: [4])
    global_block_indices: List[int] = field(default_factory=lambda: [0])
    global_block_end_indices: Optional[List[int]] = None
    num_sliding_window_blocks: int = 3

    @staticmethod
    def from_dict(d: Optional[Dict]) -> Optional["SparseAttentionConfig"]:
        if d is None:
            return None
        cfg = SparseAttentionConfig()
        for k, v in d.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        return cfg


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "FlopsProfilerConfig":
        if not d:
            return FlopsProfilerConfig()
        return FlopsProfilerConfig(
            enabled=get_scalar_param(d, C.FLOPS_PROFILER_ENABLED, False),
            profile_step=get_scalar_param(d, C.FLOPS_PROFILER_PROFILE_STEP, 1),
            module_depth=get_scalar_param(d, C.FLOPS_PROFILER_MODULE_DEPTH, -1),
            top_modules=get_scalar_param(d, C.FLOPS_PROFILER_TOP_MODULES, 1),
            detailed=get_scalar_param(d, C.FLOPS_PROFILER_DETAILED, True),
            output_file=get_scalar_param(d, C.FLOPS_PROFILER_OUTPUT_FILE, None),
        )


@dataclass
class TensorboardConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = C.TENSORBOARD_JOB_NAME_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "TensorboardConfig":
        if not d:
            return TensorboardConfig()
        return TensorboardConfig(
            enabled=get_scalar_param(d, C.TENSORBOARD_ENABLED, False),
            output_path=get_scalar_param(d, C.TENSORBOARD_OUTPUT_PATH, ""),
            job_name=get_scalar_param(d, C.TENSORBOARD_JOB_NAME,
                                      C.TENSORBOARD_JOB_NAME_DEFAULT),
        )


@dataclass
class PLDConfig:
    enabled: bool = False
    theta: float = 1.0
    gamma: float = 0.001

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "PLDConfig":
        if not d:
            return PLDConfig()
        return PLDConfig(
            enabled=get_scalar_param(d, C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT),
            theta=get_scalar_param(d, C.PLD_THETA, C.PLD_THETA_DEFAULT),
            gamma=get_scalar_param(d, C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT),
        )


@dataclass
class CurriculumConfig:
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "CurriculumConfig":
        if not d:
            return CurriculumConfig()
        return CurriculumConfig(
            enabled=get_scalar_param(d, C.CURRICULUM_ENABLED, False),
            curriculum_type=get_scalar_param(d, "curriculum_type", "seqlen"),
            min_difficulty=get_scalar_param(d, "min_difficulty", 8),
            max_difficulty=get_scalar_param(d, "max_difficulty", 1024),
            schedule_type=get_scalar_param(d, "schedule_type", "fixed_linear"),
            schedule_config=d.get("schedule_config", {}),
        )


@dataclass
class EigenvalueConfig:
    """MoQ eigenvalue config (ref: deepspeed/runtime/eigenvalue.py:7)."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "EigenvalueConfig":
        if not d:
            return EigenvalueConfig()
        cfg = EigenvalueConfig()
        for k, v in d.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        return cfg


@dataclass
class QuantizeTrainingConfig:
    """MoQ quantize-aware-training config (ref: deepspeed/runtime/quantize.py:12
    and config parsing in deepspeed/runtime/config.py get_quantize_training)."""
    enabled: bool = False
    quantize_bits_start: int = 16
    quantize_bits_target: int = 8
    quantize_schedule_offset: int = 100
    quantize_groups: int = 1
    quantize_period: int = 100
    schedule_type: str = "linear"   # linear | exponential
    quantize_type: str = "symmetric"  # symmetric | asymmetric
    rounding: str = "nearest"       # nearest | stochastic
    fp16_mixed_quantize: bool = False
    quantize_change_ratio: float = 0.001
    quantize_verbose: bool = False
    use_quantizer_kernel: bool = True
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "QuantizeTrainingConfig":
        if not d:
            return QuantizeTrainingConfig()
        cfg = QuantizeTrainingConfig()
        for k, v in d.items():
            if k == "eigenvalue":
                cfg.eigenvalue = EigenvalueConfig.from_dict(v)
            elif hasattr(cfg, k):
                setattr(cfg, k, v)
        cfg.enabled = d.get("enabled", False)
        return cfg


@dataclass
class OptimizerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "OptimizerConfig":
        if not d:
            return OptimizerConfig()
        return OptimizerConfig(
            type=d.get(C.TYPE),
            params=d.get(C.OPTIMIZER_PARAMS, {}) or {},
            legacy_fusion=d.get(C.LEGACY_FUSION, False),
        )


@dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Optional[Dict]) -> "SchedulerConfig":
        if not d:
            return SchedulerConfig()
        return SchedulerConfig(type=d.get(C.TYPE), params=d.get(C.SCHEDULER_PARAMS, {}) or {})


class DeepSpeedConfig:
    """Typed view over the JSON config (ref: deepspeed/runtime/config.py:791).

    Parameters
    ----------
    config : str | dict
        Path to a JSON file or an already-parsed dict.
    world_size : int
        Number of chips participating in data parallelism (used for
        batch-size reconciliation). On TPU this is
        ``mesh data-axis size x fsdp-axis size``.
    """

    def __init__(self, config: Union[str, Dict], world_size: int = 1):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(
                    f"Expected a string path to an existing deepspeed config, "
                    f"but received: {config}")
            with open(config) as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = config
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path or dict, got {type(config)}")

        self.world_size = world_size
        self._initialize(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # ------------------------------------------------------------------
    def _initialize(self, pd: Dict):
        self.train_batch_size = get_scalar_param(pd, C.TRAIN_BATCH_SIZE,
                                                 C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            pd, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_scalar_param(
            pd, C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)

        self.steps_per_print = get_scalar_param(pd, C.STEPS_PER_PRINT,
                                                C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.gradient_clipping = get_scalar_param(pd, C.GRADIENT_CLIPPING,
                                                  C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, C.PRESCALE_GRADIENTS,
                                                   C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            pd, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(pd, C.SPARSE_GRADIENTS,
                                                         C.SPARSE_GRADIENTS_DEFAULT)
        self.wall_clock_breakdown = get_scalar_param(pd, C.WALL_CLOCK_BREAKDOWN,
                                                     C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(pd, C.MEMORY_BREAKDOWN,
                                                 C.MEMORY_BREAKDOWN_DEFAULT)
        self.seed = get_scalar_param(pd, C.SEED, C.SEED_DEFAULT)

        self.fp16 = FP16Config.from_dict(pd.get(C.FP16, {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16 = BF16Config.from_dict(bf16_dict)
        self.zero = ZeroConfig.from_dict(pd.get(C.ZERO_OPTIMIZATION))
        self.lora = LoraConfig.from_dict(pd.get(C.LORA))
        self.mesh = MeshConfig.from_dict(pd.get(C.MESH))
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(
            pd.get(C.ACTIVATION_CHECKPOINTING))
        self.sparse_attention = SparseAttentionConfig.from_dict(pd.get(C.SPARSE_ATTENTION))
        self.flops_profiler = FlopsProfilerConfig.from_dict(pd.get(C.FLOPS_PROFILER))
        self.tensorboard = TensorboardConfig.from_dict(pd.get(C.TENSORBOARD))
        self.pld = PLDConfig.from_dict(pd.get(C.PROGRESSIVE_LAYER_DROP))
        self.curriculum = CurriculumConfig.from_dict(pd.get(C.CURRICULUM_LEARNING))
        self.quantize_training = QuantizeTrainingConfig.from_dict(pd.get(C.QUANTIZE_TRAINING))
        self.optimizer = OptimizerConfig.from_dict(pd.get(C.OPTIMIZER))
        self.scheduler = SchedulerConfig.from_dict(pd.get(C.SCHEDULER))

        self.checkpoint_tag_validation_mode = get_scalar_param(
            pd.get(C.CHECKPOINT, {}) or {}, C.CHECKPOINT_TAG_VALIDATION,
            C.CHECKPOINT_TAG_VALIDATION_DEFAULT).lower().capitalize()

        self.elasticity_enabled = bool(
            (pd.get(C.ELASTICITY) or {}).get(C.ELASTICITY_ENABLED,
                                             C.ELASTICITY_ENABLED_DEFAULT))
        self.elasticity_dict = pd.get(C.ELASTICITY) or {}
        self.autotuning_enabled = bool(
            (pd.get(C.AUTOTUNING) or {}).get(C.AUTOTUNING_ENABLED, False))
        self.autotuning_dict = pd.get(C.AUTOTUNING) or {}

        self.comm_backend_name = get_scalar_param(pd, C.COMM_BACKEND_NAME,
                                                  C.COMM_BACKEND_NAME_DEFAULT)

        dtypes = pd.get(C.DATA_TYPES, {}) or {}
        self.grad_accum_dtype = dtypes.get(C.GRAD_ACCUM_DTYPE, C.GRAD_ACCUM_DTYPE_DEFAULT)

    # ------------------------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.fp16.enabled:
            return jnp.float16
        if self.bf16.enabled:
            return jnp.bfloat16
        return jnp.float32

    @property
    def precision_name(self) -> str:
        if self.fp16.enabled:
            return "fp16"
        if self.bf16.enabled:
            return "bf16"
        return "fp32"

    # ------------------------------------------------------------------
    def _configure_train_batch_size(self):
        """Reconcile train_batch = micro_batch * grad_acc * dp_world
        (ref: deepspeed/runtime/config.py _configure_train_batch_size)."""
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        ws = self.world_size

        if all(x is not None for x in (train_batch, micro_batch, grad_acc)):
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= ws
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // ws
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // ws
        elif micro_batch is not None:
            if grad_acc is None:
                self.gradient_accumulation_steps = 1
            self.train_batch_size = (self.train_micro_batch_size_per_gpu *
                                     self.gradient_accumulation_steps * ws)
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal to "
            f"micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    # ------------------------------------------------------------------
    def _do_sanity_check(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 modes cannot both be enabled")
        if self.zero.stage >= 2 and self.fp16.enabled is False and self.bf16.enabled is False:
            logger.warning("ZeRO with fp32 enabled — allowed, but mixed "
                           "precision is recommended on TPU (bf16)")
        if self.checkpoint_tag_validation_mode not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint_tag_validation mode "
                f"{self.checkpoint_tag_validation_mode} invalid, must be one of "
                f"{C.CHECKPOINT_TAG_VALIDATION_MODES}")

    # ------------------------------------------------------------------
    def print_config(self, name: str = "DeepSpeedTPUConfig"):
        logger.info(f"{name}:")
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True, default=str))

    @property
    def param_dict(self) -> Dict:
        return self._param_dict
