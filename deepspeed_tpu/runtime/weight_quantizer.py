"""Inference weight quantization (int8 storage + merged scales).

Capability match for the reference's ``WeightQuantization``
(ref: deepspeed/runtime/weight_quantizer.py:5): group-wise symmetric
quantization of transformer weights at checkpoint-load time, with extra
grouping for MLP matrices and per-layer scale merging for the fused
inference kernels.

TPU-native: weights live as int8 jax arrays + float32 scales; matmuls
dequantize on the fly (XLA fuses the rescale into the HBM→MXU load),
halving weight HBM traffic — the same win the reference's int8 GEMMs
target. Scale bookkeeping keeps the reference's category split
(qkv / dense / mlp h→4h / mlp 4h→h) and merge layout.
"""

from typing import List, Tuple

import jax.numpy as jnp

from deepspeed_tpu.ops import quantizer as qops


class WeightQuantization:
    def __init__(self, mlp_extra_grouping: bool = True, mp_size: int = 1):
        self.dense_scales: List[jnp.ndarray] = []
        self.qkv_scales: List[jnp.ndarray] = []
        self.mlp4hh_scales: List[jnp.ndarray] = []
        self.mlph4h_scales: List[jnp.ndarray] = []
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = mp_size

    # shape heuristics (ref: weight_quantizer.py:29-36)
    def is_mlp(self, data, merge_count: int = 1) -> bool:
        return ((self.mp_size * data.shape[0] * merge_count) / data.shape[1] == 4
                or (self.mp_size * data.shape[1] * merge_count) / data.shape[0] == 4)

    def is_qkv(self, data) -> bool:
        return ((self.mp_size * data.shape[0]) / data.shape[1] == 3
                or (self.mp_size * data.shape[1]) / data.shape[0] == 3)

    def quantize_data(self, data: jnp.ndarray, quantize_bits: int,
                      groups: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One tensor → (int8 tensor, per-group scale); ``x ≈ q/scale``
        (ref: weight_quantizer.py:14 quantize_data)."""
        return qops.quantize(data, groups=groups, bits=quantize_bits)

    def Quantize(self, value_list: List[jnp.ndarray], quantize_bits: int,
                 groups: int, key: str) -> List[jnp.ndarray]:
        """Quantize a (possibly TP-split) list of weights for one layer
        slot, recording inverse scales by category
        (ref: weight_quantizer.py:37)."""
        if self.mlp_extra_grouping and \
                self.is_mlp(value_list[0], merge_count=len(value_list)):
            groups *= 2
        q_scale = []
        for index, data in enumerate(value_list):
            data_int, data_scale = self.quantize_data(data, quantize_bits, groups)
            q_scale.append(data_scale)
            value_list[index] = data_int
        q_scale = 1.0 / jnp.concatenate(q_scale).reshape(1, -1)
        if "mlp.dense_4h_to_h.weight" in key or "fc_out" in key:
            self.mlp4hh_scales.append(q_scale)
        elif "mlp.dense_h_to_4h.weight" in key or "fc_in" in key:
            self.mlph4h_scales.append(q_scale)
        elif "query_key_value" in key or "qkv" in key:
            self.qkv_scales.append(q_scale)
        else:
            self.dense_scales.append(q_scale)
        return value_list

    def merge_layer_scales(self, layer_scales: List[jnp.ndarray]) -> jnp.ndarray:
        """Pad per-category scales to a common width and stack
        (ref: weight_quantizer.py:61)."""
        max_dim = max(s.shape[-1] for s in layer_scales)
        padded = [
            jnp.concatenate(
                [s, jnp.zeros((1, max_dim - s.shape[-1]), s.dtype)], axis=-1)
            if s.shape[-1] < max_dim else s for s in layer_scales
        ]
        return jnp.concatenate(padded)[None, ...]

    def merge_scales(self) -> jnp.ndarray:
        all_scales = []
        for dense_scale, qkv_scale, m4hh_scale, mh4h_scale in zip(
                self.dense_scales, self.qkv_scales,
                self.mlp4hh_scales, self.mlph4h_scales):
            all_scales.append(self.merge_layer_scales(
                [qkv_scale, dense_scale, mh4h_scale, m4hh_scale]))
        return jnp.concatenate(all_scales)

    def merge_scales_split(self, split_count: int) -> List[jnp.ndarray]:
        """Per-TP-rank scale split (ref: weight_quantizer.py:84).

        Each category's *real* scale row is split into split_count chunks
        first, and only then padded to the per-rank common width — splitting
        the padded merge instead would hand non-zero ranks the padding zeros
        whenever category widths differ (always with mlp_extra_grouping).
        """
        per_rank: List[List[jnp.ndarray]] = [[] for _ in range(split_count)]
        for dense_scale, qkv_scale, m4hh_scale, mh4h_scale in zip(
                self.dense_scales, self.qkv_scales,
                self.mlp4hh_scales, self.mlph4h_scales):
            cat_chunks = [jnp.split(s, split_count, axis=-1)
                          for s in (qkv_scale, dense_scale,
                                    mh4h_scale, m4hh_scale)]
            for rank in range(split_count):
                per_rank[rank].append(self.merge_layer_scales(
                    [chunks[rank] for chunks in cat_chunks]))
        return [jnp.concatenate(rows) for rows in per_rank]
