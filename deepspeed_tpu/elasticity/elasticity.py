"""Elasticity v0.1 — batch-size elasticity across restarts.

Capability match for the reference's elasticity module
(ref: deepspeed/elasticity/elasticity.py:226 compute_elastic_config,
:128 _get_compatible_gpus_v01): given acceptable micro-batch sizes and a
max global batch size, compute ONE fixed global batch size plus the
list of chip counts that divide it evenly — so a resource scheduler can
scale the job up/down across restarts with zero convergence impact
(global batch = micro_batch x grad_accum x world stays constant).

This is *not* in-job fault tolerance (neither is the reference's);
recovery remains checkpoint-resume. TPU addition: slices come in fixed
topologies, so ``allowed_chip_counts`` (e.g. {1,4,8,16,32,...} for v5e
slice shapes) optionally filters the valid counts to reachable slice
sizes.
"""

import json
import math
import os
from functools import reduce
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import __version__

ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MICRO_BATCHES = "micro_batch_sizes"
MIN_CHIPS, MAX_CHIPS = "min_gpus", "max_gpus"  # reference key names kept
MIN_TIME = "min_time"
VERSION = "version"
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
LATEST_ELASTICITY_VERSION = 0.1
MINIMUM_DEEPSPEED_VERSION = "0.1.0"
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"

# Thirty-eight smallest highly composite numbers — supports batch sizes
# up to 720K (ref: elasticity.py:20 HCN_LIST)
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
    1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
    50400, 55440, 83160, 110880, 166320, 221760, 277200, 332640, 498960,
    554400, 665280, 720720
]


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """(ref: elasticity/config.py:27) validated elastic sub-config."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity config missing {MAX_ACCEPTABLE_BATCH_SIZE}")
            if MICRO_BATCHES not in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity config missing {MICRO_BATCHES}")
        self.max_acceptable_batch_size = param_dict.get(
            MAX_ACCEPTABLE_BATCH_SIZE, 2000)
        self.micro_batches = param_dict.get(MICRO_BATCHES, [2, 4, 6])
        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"{MICRO_BATCHES} must be a list, got "
                f"{type(self.micro_batches)}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{MICRO_BATCHES} must be positive ints: {self.micro_batches}")
        self.min_gpus = param_dict.get(MIN_CHIPS, 1)
        self.max_gpus = param_dict.get(MAX_CHIPS, -1)
        self.min_time = param_dict.get(MIN_TIME, 0)
        self.version = param_dict.get(VERSION, LATEST_ELASTICITY_VERSION)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, False)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.allowed_chip_counts = param_dict.get("allowed_chip_counts")

    def repr(self) -> Dict:
        return self.__dict__


def get_candidate_batch_sizes(base_list: Sequence[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """Scale each base by the largest HCN that keeps the product under
    the cap (ref: elasticity.py:63)."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
        else:
            value = max_acceptable_batch_size // base
            hcn = max(h for h in HCN_LIST if h <= value)
            candidates.add(hcn * base)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: Sequence[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """All chip counts w such that batch_size == micro * gas * w for some
    acceptable micro and integer gas (ref: elasticity.py:77)."""
    valid = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_chips = batch_size // micro_batch
        if min_valid_gpus <= max_chips <= max_valid_gpus:
            valid.add(max_chips)
        for i in range(1, max_chips // 2 + 1):
            if i > max_valid_gpus:
                break
            if i < min_valid_gpus:
                continue
            if max_chips % i == 0:
                valid.add(i)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes: Sequence[int],
                        micro_batches: Sequence[int], min_gpus: int,
                        max_gpus: int, prefer_larger: bool
                        ) -> Tuple[int, Optional[List[int]]]:
    """Pick the candidate with the most compatible chip counts
    (ref: elasticity.py:100)."""
    max_valid = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if (len(current) > max_valid
                or (len(current) == max_valid
                    and ((prefer_larger and batch_size > final_batch_size)
                         or (not prefer_larger
                             and batch_size < final_batch_size)))):
            max_valid = len(current)
            valid_gpus = current
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches: Sequence[int],
                             max_acceptable_batch_size: int,
                             min_gpus: Optional[int] = None,
                             max_gpus: Optional[int] = None,
                             prefer_larger: bool = True
                             ) -> Tuple[int, Optional[List[int]]]:
    """v0.1 heuristic (ref: elasticity.py:128): candidates = each micro
    batch and their LCM, each scaled by highly-composite multipliers;
    winner maximizes the count of compatible chip counts."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus if max_gpus and max_gpus > 0 else \
        max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            "All micro batches must be <= max_acceptable_batch_size "
            f"({max_acceptable_batch_size}): {micro_batches}")
    lcm = reduce(lambda a, b: a * b // math.gcd(a, b), micro_batches)
    base_list = list(micro_batches) + [lcm]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus,
                               max_gpus, prefer_larger)


def elasticity_enabled(ds_config: Dict) -> bool:
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """Cross-check the scheduler's view (env) against the runtime config
    (ref: elasticity.py:192)."""
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:  # dslint: disable=DS005 — the scheduler hands its view over via env by contract
        scheduler = ElasticityConfig(
            json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))  # dslint: disable=DS005
        runtime = ElasticityConfig(runtime_elastic_config_dict)
        for field in ("max_acceptable_batch_size", "micro_batches", "version"):
            if getattr(runtime, field) != getattr(scheduler, field):
                raise ElasticityConfigError(
                    f"Elastic config '{field}={getattr(scheduler, field)}' "
                    f"seen by resource scheduler does not match runtime "
                    f"{field}={getattr(runtime, field)}")
    else:
        logger.warning(
            "DEEPSPEED_ELASTICITY_CONFIG env not found; cannot guarantee "
            "the resource scheduler will scale with compatible chip counts.")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str,
                           world_size: int = 0,
                           allowed_chip_counts: Optional[Set[int]] = None):
    """Core elasticity API (ref: elasticity.py:226). Returns
    (final_batch_size, valid_chip_counts, micro_batch_for_world) — the
    third only when ``world_size`` is given.

    ``allowed_chip_counts``: optional TPU slice-shape filter (a v5e pod
    only offers 1/4/8/16/..., so other divisor counts are unreachable).
    """
    elastic_config_dict = ds_config.get(ELASTICITY)
    if not elastic_config_dict:
        raise ElasticityConfigError(
            f"'{ELASTICITY}' is missing from config json")
    elastic_config = ElasticityConfig(elastic_config_dict)
    if not elastic_config.enabled:
        raise ElasticityConfigError("Elasticity is not enabled")
    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(
            f"Unsupported elasticity version {elastic_config.version}")

    final_batch_size, valid_gpus = _get_compatible_gpus_v01(
        micro_batches=elastic_config.micro_batches,
        max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
        min_gpus=elastic_config.min_gpus,
        max_gpus=elastic_config.max_gpus,
        prefer_larger=elastic_config.prefer_larger_batch_size)

    allowed = allowed_chip_counts or elastic_config.allowed_chip_counts
    if allowed:
        valid_gpus = sorted(set(valid_gpus) & set(allowed))
        if not valid_gpus:
            raise ElasticityError(
                "no compatible chip count is an allowed slice shape")

    logger.info(f"elastic config: final_batch_size={final_batch_size}, "
                f"valid chip counts={valid_gpus}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current "
                f"list of valid chip counts: {valid_gpus}")
        # pick the largest micro batch that fits evenly on this world
        micro = None
        for mb in sorted(elastic_config.micro_batches, reverse=True):
            if final_batch_size // world_size % mb == 0:
                micro = mb
                break
        if micro is None:
            # surfacing it here beats a silent None propagating into the
            # batch-triple reconciliation (ref: elasticity.py:378 asserts
            # micro_batch is not None)
            raise ElasticityError(
                f"no micro batch from {elastic_config.micro_batches} divides "
                f"per-chip batch {final_batch_size // world_size} at world "
                f"size {world_size}")
        return final_batch_size, valid_gpus, micro

    return final_batch_size, valid_gpus
