"""Tuner strategies: grid / random / model-based.

Capability match for the reference's tuner package
(ref: deepspeed/autotuning/tuner/base_tuner.py:11 BaseTuner,
index_based_tuner.py:8,23 Random/GridSearchTuner,
model_based_tuner.py:16 ModelBasedTuner).
"""

import random
from typing import Dict, List, Optional

from deepspeed_tpu.autotuning.cost_model import default_cost_model
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.autotuning.utils import dict_to_feature, flatten
from deepspeed_tpu.utils.logging import logger


class BaseTuner:
    def __init__(self, exps: List[Experiment], resource_manager: ResourceManager,
                 metric: str = "throughput"):
        self.all_exps = exps
        self.rm = resource_manager
        self.metric = metric
        self.best_iter = 0
        self.best_exp: Optional[Experiment] = None
        self.best_metric_val: Optional[float] = None

    def has_next(self) -> bool:
        return len(self.all_exps) > 0

    def next_batch(self, sample_size: int) -> List[Experiment]:
        raise NotImplementedError

    def update(self) -> None:
        """Incorporate the newest results (model-based overrides)."""

    def tune(self, sample_size: int = 1, n_trials: int = 1000,
             early_stopping: Optional[int] = None) -> int:
        """(ref: base_tuner.py:35) returns number of experiments run."""
        i = 0
        while i < n_trials and self.has_next():
            sampled = self.next_batch(sample_size)
            self.rm.schedule_experiments(sampled)
            self.rm.run()
            for exp in self.rm.finished_experiments[-len(sampled):]:
                if exp.metric_val is not None and (
                        self.best_metric_val is None
                        or exp.metric_val > self.best_metric_val):
                    self.best_exp = exp
                    self.best_metric_val = exp.metric_val
                    self.best_iter = i
            i += len(sampled)
            self.update()
            if early_stopping and i >= self.best_iter + early_stopping:
                logger.info(
                    f"early stop: no improvement in {early_stopping} exps")
                break
        return i


class GridSearchTuner(BaseTuner):
    """In-order exhaustive sweep (ref: index_based_tuner.py:23)."""

    def next_batch(self, sample_size: int = 1) -> List[Experiment]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    """Random order without replacement (ref: index_based_tuner.py:8)."""

    def __init__(self, exps, resource_manager, metric="throughput", seed=0):
        super().__init__(list(exps), resource_manager, metric)
        self._rng = random.Random(seed)

    def next_batch(self, sample_size: int = 1) -> List[Experiment]:
        sample_size = min(sample_size, len(self.all_exps))
        batch = self._rng.sample(self.all_exps, sample_size)
        for b in batch:
            self.all_exps.remove(b)
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model guided search (ref: model_based_tuner.py:16): run a
    random warmup, fit the model on (features -> metric), then greedily
    pick the predicted-best remaining configs, refitting as results
    arrive."""

    def __init__(self, exps, resource_manager, metric="throughput",
                 warmup: int = 3, seed: int = 0):
        super().__init__(list(exps), resource_manager, metric)
        self.warmup = warmup
        self._rng = random.Random(seed)
        self.cost_model = default_cost_model()
        keys = set()
        for e in self.all_exps:
            keys.update(flatten(e.ds_config).keys())
        self.feature_keys = sorted(keys)
        self._trained = False

    def _features(self, exp: Experiment) -> List[float]:
        return dict_to_feature(flatten(exp.ds_config), self.feature_keys)

    def next_batch(self, sample_size: int = 1) -> List[Experiment]:
        sample_size = min(sample_size, len(self.all_exps))
        n_done = len(self.rm.finished_experiments)
        if n_done < self.warmup or not self._trained:
            batch = self._rng.sample(self.all_exps, sample_size)
        else:
            preds = self.cost_model.predict(
                [self._features(e) for e in self.all_exps])
            order = sorted(range(len(self.all_exps)),
                           key=lambda i: -preds[i])
            batch = [self.all_exps[i] for i in order[:sample_size]]
        for b in batch:
            self.all_exps.remove(b)
        return batch

    def update(self) -> None:
        done = [e for e in self.rm.finished_experiments
                if e.metric_val is not None]
        if len(done) >= max(2, self.warmup):
            xs = [self._features(e) for e in done]
            ys = [e.metric_val for e in done]
            self.cost_model.fit(xs, ys)
            self._trained = True
