"""Experiment scheduler.

Capability match for the reference's ``ResourceManager``
(ref: deepspeed/autotuning/scheduler.py:35): owns the experiment queue,
dispatches experiments, records results. The reference launches each
experiment as a multi-node job over a hostfile; on a TPU host the
experiment is an in-process engine build + timed steps, so the runner
is a callable — the queue/records/result-path API stays.
"""

import json
import os
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class Experiment:
    def __init__(self, name: str, ds_config: Dict):
        self.name = name
        self.ds_config = ds_config
        self.done = False
        self.metric_val: Optional[float] = None
        self.error: Optional[str] = None

    def as_record(self) -> Dict[str, Any]:
        return {"name": self.name, "ds_config": self.ds_config,
                "metric_val": self.metric_val, "error": self.error}


class ResourceManager:
    """Runs experiments through ``runner(ds_config) -> float`` and keeps
    records (ref: scheduler.py:35; `parse_results` :183)."""

    def __init__(self, runner: Callable[[Dict], float],
                 results_dir: Optional[str] = None):
        self.runner = runner
        self.results_dir = results_dir
        self.experiment_queue: List[Experiment] = []
        self.finished_experiments: List[Experiment] = []
        if results_dir:
            os.makedirs(results_dir, exist_ok=True)

    def schedule_experiments(self, exps) -> None:
        for e in exps:
            self.experiment_queue.append(e)

    def run(self) -> None:
        while self.experiment_queue:
            exp = self.experiment_queue.pop(0)
            try:
                exp.metric_val = float(self.runner(exp.ds_config))
            except Exception as err:  # OOM/compile failure = experiment loss
                exp.error = f"{type(err).__name__}: {err}"
                exp.metric_val = None
                logger.warning(f"experiment {exp.name} failed: {exp.error}")
            exp.done = True
            self.finished_experiments.append(exp)
            if self.results_dir:
                path = os.path.join(self.results_dir, f"{exp.name}.json")
                with open(path, "w") as f:
                    json.dump(exp.as_record(), f, indent=2)

    def clear(self) -> None:
        self.experiment_queue.clear()

    def best(self) -> Optional[Experiment]:
        done = [e for e in self.finished_experiments
                if e.metric_val is not None]
        return max(done, key=lambda e: e.metric_val) if done else None
