"""Experiment scheduler.

Capability match for the reference's ``ResourceManager``
(ref: deepspeed/autotuning/scheduler.py:35): owns the experiment queue,
dispatches experiments, records results. Two dispatch modes:

- an in-process callable (fresh engine + timed steps) for cheap local
  sweeps, and
- ``SubprocessRunner`` — each experiment in its own OS process with a
  wall-clock timeout and OOM/compile-failure classification, the analog
  of the reference launching every experiment as a separate job
  (ref: scheduler.py:35 run_job + :183 parse_results). Process
  isolation is what makes unattended tuning safe here: a diverging
  candidate, a borderline-HBM compile, or a wedged remote compile
  helper costs its own timeout, never the tuning loop.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class Experiment:
    def __init__(self, name: str, ds_config: Dict):
        self.name = name
        self.ds_config = ds_config
        self.done = False
        self.metric_val: Optional[float] = None
        self.error: Optional[str] = None

    def as_record(self) -> Dict[str, Any]:
        return {"name": self.name, "ds_config": self.ds_config,
                "metric_val": self.metric_val, "error": self.error}


class ExperimentError(RuntimeError):
    """A failed experiment with a classified kind: 'timeout' (hung or
    over-budget), 'oom' (device/host memory exhaustion), or 'error'
    (everything else). The tuning loop treats all three as a lost
    experiment, but the kind is recorded so an unattended sweep's log
    shows WHY configs were rejected (ref: the reference's per-job
    error capture in scheduler.py:128 run_job)."""

    def __init__(self, kind: str, detail: str):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind
        self.detail = detail


_OOM_MARKERS = ("resource_exhausted", "out of memory", "memoryerror",
                "failed to allocate", "hbm limit")
# the bare marker needs word boundaries: "bloom"/"zoom" in a model name
# or log line must not classify an ordinary failure as out-of-memory
_OOM_RE = re.compile(r"\boom\b")


def _is_oom(blob: str) -> bool:
    return any(m in blob for m in _OOM_MARKERS) or bool(_OOM_RE.search(blob))


class SubprocessRunner:
    """Run each experiment in its own OS process with a timeout.

    Exactly one of ``cmd`` / ``cmd_builder``:
    - ``cmd``: argv prefix; the experiment's ds_config is written to a
      temp JSON file whose path is appended (the reference's pattern of
      materializing exp_dir/ds_config.json per job, scheduler.py:35).
    - ``cmd_builder(ds_config) -> argv``: full control (e.g. embedding
      the spec in a ``python -c`` template).

    The child must print a JSON line ``{"metric": <float>}`` (override
    with ``parse(stdout) -> float`` for other formats). Non-zero exit,
    hang, or unparsable output raise ``ExperimentError`` with a
    classified kind.
    """

    def __init__(self, cmd: Optional[List[str]] = None, *,
                 cmd_builder: Optional[Callable[[Dict], List[str]]] = None,
                 parse: Optional[Callable[[str], float]] = None,
                 timeout_s: float = 1800.0, env: Optional[Dict] = None,
                 cwd: Optional[str] = None):
        assert (cmd is None) != (cmd_builder is None), \
            "exactly one of cmd / cmd_builder"
        self.cmd = cmd
        self.cmd_builder = cmd_builder
        self.parse = parse or self._parse_metric_line
        self.timeout_s = timeout_s
        self.env = env
        self.cwd = cwd
        self.last_stdout: str = ""

    @staticmethod
    def _parse_metric_line(stdout: str) -> float:
        for line in reversed(stdout.splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    return float(json.loads(line)["metric"])
                except (ValueError, KeyError, TypeError):
                    continue
        raise ExperimentError("error", "no {\"metric\": ...} line in output")

    def __call__(self, ds_config: Dict) -> float:
        tmp = None
        if self.cmd_builder is not None:
            argv = self.cmd_builder(ds_config)
        else:
            fd, tmp = tempfile.mkstemp(suffix=".json", prefix="ds_exp_")
            with os.fdopen(fd, "w") as f:
                json.dump(ds_config, f)
            argv = list(self.cmd) + [tmp]
        env = dict(os.environ) if self.env is None else dict(self.env)
        try:
            try:
                r = subprocess.run(argv, capture_output=True, text=True,
                                   timeout=self.timeout_s, env=env,
                                   cwd=self.cwd)
            except subprocess.TimeoutExpired:
                raise ExperimentError(
                    "timeout", f"exceeded {self.timeout_s:.0f}s wall clock")
            self.last_stdout = r.stdout or ""
            if r.returncode != 0:
                blob = ((r.stderr or "") + (r.stdout or "")).lower()
                kind = "oom" if _is_oom(blob) else "error"
                raise ExperimentError(
                    kind, f"rc={r.returncode}: {(r.stderr or '')[-400:]}")
            return float(self.parse(self.last_stdout))
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass


class ResourceManager:
    """Runs experiments through ``runner(ds_config) -> float`` and keeps
    records (ref: scheduler.py:35; `parse_results` :183)."""

    def __init__(self, runner: Callable[[Dict], float],
                 results_dir: Optional[str] = None):
        self.runner = runner
        self.results_dir = results_dir
        self.experiment_queue: List[Experiment] = []
        self.finished_experiments: List[Experiment] = []
        if results_dir:
            os.makedirs(results_dir, exist_ok=True)

    def schedule_experiments(self, exps) -> None:
        for e in exps:
            self.experiment_queue.append(e)

    def run(self) -> None:
        while self.experiment_queue:
            exp = self.experiment_queue.pop(0)
            try:
                exp.metric_val = float(self.runner(exp.ds_config))
            except Exception as err:  # OOM/compile failure = experiment loss
                exp.error = f"{type(err).__name__}: {err}"
                exp.metric_val = None
                logger.warning(f"experiment {exp.name} failed: {exp.error}")
            exp.done = True
            self.finished_experiments.append(exp)
            if self.results_dir:
                path = os.path.join(self.results_dir, f"{exp.name}.json")
                with open(path, "w") as f:
                    json.dump(exp.as_record(), f, indent=2)

    def clear(self) -> None:
        self.experiment_queue.clear()

    def best(self) -> Optional[Experiment]:
        done = [e for e in self.finished_experiments
                if e.metric_val is not None]
        return max(done, key=lambda e: e.metric_val) if done else None
