"""Autotuner helpers (ref: deepspeed/autotuning/tuner/utils.py and
autotuning/utils.py): tuning-space combinatorics and feature vectors."""

import itertools
from typing import Any, Dict, List


def flatten(d: Dict, parent_key: str = "", sep: str = "_") -> Dict:
    """Nested dict -> flat key dict (ref: tuner/utils.py:52)."""
    items = []
    for k, v in d.items():
        new_key = parent_key + sep + k if parent_key else k
        if isinstance(v, dict):
            items.extend(flatten(v, new_key, sep=sep).items())
        else:
            items.append((new_key, v))
    return dict(items)


def gen_combinations(d: Dict) -> List[Dict]:
    """Cartesian product over every list-valued key of a (nested)
    tuning space (ref: tuner/utils.py:40)."""
    keys, values = [], []
    for k, v in d.items():
        if isinstance(v, dict):
            keys.append(k)
            values.append(gen_combinations(v))
        else:
            keys.append(k)
            values.append(v if isinstance(v, list) else [v])
    out = []
    for combo in itertools.product(*values):
        out.append(dict(zip(keys, combo)))
    return out


def dict_to_feature(feature_dict: Dict, keys: List[str]) -> List[float]:
    """Flat config -> numeric feature vector for the cost model
    (ref: tuner/utils.py:63); non-numeric values hash to small ints."""
    feat = []
    for k in keys:
        v = feature_dict.get(k, 0)
        if isinstance(v, bool):
            feat.append(float(v))
        elif isinstance(v, (int, float)):
            feat.append(float(v))
        elif v is None:
            feat.append(0.0)
        else:
            feat.append(float(abs(hash(str(v))) % 97))
    return feat


def deep_update(base: Dict, overrides: Dict) -> Dict:
    """Return base with nested overrides applied. Every nested dict is
    copied (never aliased) so callers can mutate the result freely."""
    out = {k: (deep_update(v, {}) if isinstance(v, dict) else v)
           for k, v in base.items()}
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_update(out[k], v)
        elif isinstance(v, dict):
            out[k] = deep_update(v, {})
        else:
            out[k] = v
    return out


def canonical_name(exp_config: Dict) -> str:
    """Stable short name for an experiment (ref: autotuning/utils.py
    canonical_name): z<stage>_mbs<micro>_gas<gas>."""
    z = (exp_config.get("zero_optimization") or {}).get("stage", 0)
    mbs = exp_config.get("train_micro_batch_size_per_gpu", "auto")
    gas = exp_config.get("gradient_accumulation_steps", 1)
    return f"z{z}_mbs{mbs}_gas{gas}"
