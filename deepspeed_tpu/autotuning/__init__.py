from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.autotuning.tuner import (
    BaseTuner, GridSearchTuner, ModelBasedTuner, RandomTuner)

__all__ = ["Autotuner", "Experiment", "ResourceManager", "BaseTuner",
           "GridSearchTuner", "ModelBasedTuner", "RandomTuner"]
