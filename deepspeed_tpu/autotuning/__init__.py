from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.scheduler import (
    Experiment, ExperimentError, ResourceManager, SubprocessRunner)
from deepspeed_tpu.autotuning.tuner import (
    BaseTuner, GridSearchTuner, ModelBasedTuner, RandomTuner)

__all__ = ["Autotuner", "Experiment", "ExperimentError", "ResourceManager",
           "SubprocessRunner", "BaseTuner", "GridSearchTuner",
           "ModelBasedTuner", "RandomTuner"]
