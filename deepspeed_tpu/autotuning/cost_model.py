"""Cost model for model-based tuning.

Capability match for the reference's ``XGBoostCostModel``
(ref: deepspeed/autotuning/tuner/cost_model.py:11). xgboost is not in
the TPU image, so the default is a closed-form ridge regression over
polynomial features — plenty for the small (tens of points) sample
sizes the tuner collects. If xgboost is importable it is used instead,
matching the reference exactly.
"""

from typing import List, Optional, Sequence

import numpy as np

try:  # pragma: no cover - depends on image contents
    import xgboost as _xgb
except ImportError:
    _xgb = None


class RidgeCostModel:
    """predict(metric | feature-vector) via ridge regression with
    degree-2 interaction features."""

    def __init__(self, alpha: float = 1e-2):
        self.alpha = alpha
        self._w: Optional[np.ndarray] = None

    @staticmethod
    def _expand(xs: np.ndarray) -> np.ndarray:
        n, d = xs.shape
        cols = [np.ones((n, 1)), xs]
        for i in range(d):
            for j in range(i, d):
                cols.append((xs[:, i] * xs[:, j])[:, None])
        return np.concatenate(cols, axis=1)

    def fit(self, xs: Sequence[Sequence[float]], ys: Sequence[float]) -> None:
        X = self._expand(np.asarray(xs, np.float64))
        y = np.asarray(ys, np.float64)
        A = X.T @ X + self.alpha * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)

    def predict(self, xs: Sequence[Sequence[float]]) -> np.ndarray:
        if self._w is None:
            return np.zeros(len(xs))
        return self._expand(np.asarray(xs, np.float64)) @ self._w


class XGBoostCostModel:  # pragma: no cover - only with xgboost present
    """Reference-faithful wrapper (ref: cost_model.py:11)."""

    def __init__(self, loss_type: str = "reg:squarederror", **kw):
        if _xgb is None:
            raise ImportError("xgboost not available; use RidgeCostModel")
        self._model = _xgb.XGBRegressor(objective=loss_type, **kw)

    def fit(self, xs, ys):
        self._model.fit(np.asarray(xs), np.asarray(ys))

    def predict(self, xs):
        return self._model.predict(np.asarray(xs))


def default_cost_model():
    if _xgb is not None:  # pragma: no cover
        return XGBoostCostModel()
    return RidgeCostModel()
