"""Autotuner: memory-model-pruned config search.

Capability match for the reference's ``Autotuner``
(ref: deepspeed/autotuning/autotuner.py:29): profile the model, prune
the (ZeRO stage x micro-batch x grad-accum) space with a memory model,
run short timed experiments through a tuner strategy
(grid/random/model-based), and emit the best config.

TPU-native differences: experiments run in-process on the local mesh (a
fresh engine + a few timed steps) instead of multi-node jobs over a
hostfile; HBM capacity comes from ``device.memory_stats()``; the
activation estimate comes from XLA cost analysis of the loss forward
instead of a profiling forward with hooks.
"""

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.autotuning.tuner import (
    BaseTuner, GridSearchTuner, ModelBasedTuner, RandomTuner)
from deepspeed_tpu.autotuning.utils import canonical_name, deep_update
from deepspeed_tpu.utils.logging import logger

AUTOTUNING = "autotuning"
METRIC_THROUGHPUT = "throughput"
METRIC_LATENCY = "latency"
METRIC_FLOPS = "flops"

DEFAULT_TUNING_SPACES = {
    0: {"zero_optimization": {"stage": 0}},
    1: {"zero_optimization": {"stage": 1}},
    2: {"zero_optimization": {"stage": 2}},
    3: {"zero_optimization": {"stage": 3}},
}

# bytes per fp32 parameter for master + Adam moments (ref:
# autotuner.py:261 get_instantiation_memory_required_per_gpu's
# 4+4+8 accounting)
OPTIM_BYTES = 12
COMPUTE_COPY_BYTES = 2   # bf16 weights materialized in the step
GRAD_BYTES = 4


class Autotuner:
    """(ref: autotuning/autotuner.py:29)

    Parameters
    ----------
    loss_fn, params : the engine contract (loss over a param pytree).
    base_config : user ds_config dict; tuned keys are overridden.
    make_batch : callable(global_batch_size) -> batch pytree.
    """

    def __init__(self, loss_fn: Callable, params, base_config: Dict,
                 make_batch: Callable[[int], Any],
                 results_dir: str = "autotuning_results"):
        import numpy as np
        self.loss_fn = loss_fn
        # host copy: each experiment's engine takes ownership of (and
        # donates) its device params, so the template must never alias
        # device buffers across experiments
        self.params = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, params)
        self.base_config = dict(base_config)
        self.make_batch = make_batch
        self.results_dir = results_dir
        at = base_config.get(AUTOTUNING, {}) or {}
        self.metric = at.get("metric", METRIC_THROUGHPUT)
        self.tuner_type = at.get("tuner_type", "model_based")
        self.tuner_early_stopping = at.get("tuner_early_stopping", 5)
        self.tuner_num_trials = at.get("tuner_num_trials", 50)
        self.num_steps = at.get("num_tuning_steps", 3)
        self.max_train_batch_size = at.get(
            "max_train_batch_size",
            base_config.get("train_batch_size"))
        self.mbs_list = at.get("micro_batch_sizes")  # explicit list wins
        self.zero_stages = at.get("zero_stages", [0, 1, 2, 3])
        self.records: Dict[str, List] = {}
        self.model_info: Dict[str, float] = {}
        self._best_exp: Optional[Experiment] = None

    # -- profiling & memory model -------------------------------------

    def get_gpu_memory_info(self) -> float:
        """Per-chip HBM bytes (ref: autotuner.py:254)."""
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                return float(stats["bytes_limit"])
        except Exception:  # dslint: disable=DS006 — probe falls back to a conservative HBM default
            pass
        return 16e9  # conservative default (v5e HBM)

    def get_model_num_params(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params)
                   if hasattr(x, "size"))

    def model_info_profile_run(self) -> Dict[str, float]:
        """(ref: autotuner.py:664) num params + activation bytes/sample
        from XLA cost analysis of the loss forward."""
        n_params = self.get_model_num_params()
        act_per_sample = 0.0
        try:
            from deepspeed_tpu.profiling.flops_profiler import analyze_fn
            dp = max(1, len(jax.devices()))
            batch = self.make_batch(dp)  # one sample per chip
            rng = jax.random.PRNGKey(0)
            prof = analyze_fn(self.loss_fn, self.params, batch, rng, runs=1)
            act_per_sample = prof["peak_bytes"] / dp
        except Exception as e:
            logger.warning(f"model-info profile failed ({e}); "
                           "activation estimate unavailable")
        self.model_info = {"num_params": n_params,
                           "activation_mem_per_gpu": act_per_sample}
        return self.model_info

    def get_instantiation_memory_required_per_gpu(self, zero_stage: int) -> float:
        """Static per-chip state bytes under each ZeRO stage
        (ref: autotuner.py:261). dp shards optimizer state at stage>=1,
        grads at >=2, params at 3."""
        n = self.model_info.get("num_params") or self.get_model_num_params()
        dp = max(1, len(jax.devices()))
        opt = OPTIM_BYTES * n / (dp if zero_stage >= 1 else 1)
        grad = GRAD_BYTES * n / (dp if zero_stage >= 2 else 1)
        master_and_copy = (4 + COMPUTE_COPY_BYTES) * n / \
            (dp if zero_stage >= 3 else 1)
        return opt + grad + master_and_copy

    def max_micro_batch_size(self, zero_stage: int) -> int:
        """Largest micro batch the memory model admits. The 0.85
        occupancy slack is stricter than the compile headroom on every
        supported device (0.15*HBM > 1.2GiB for HBM >= 8GiB), so it also
        keeps candidates out of the borderline-HBM compile regime; the
        explicit headroom check lives in tune()'s stage pruning."""
        hbm = self.get_gpu_memory_info()
        inst = self.get_instantiation_memory_required_per_gpu(zero_stage)
        act = self.model_info.get("activation_mem_per_gpu") or 0.0
        if act <= 0:
            return 64  # no estimate: bounded default sweep
        avail = hbm * 0.85 - inst
        return max(1, int(avail // act))

    # -- experiment generation ----------------------------------------

    def _micro_batch_candidates(self, zero_stage: int) -> List[int]:
        if self.mbs_list:
            return list(self.mbs_list)
        dp = max(1, len(jax.devices()))
        cap = self.max_micro_batch_size(zero_stage)
        if self.max_train_batch_size:
            cap = min(cap, max(1, self.max_train_batch_size // dp))
        out, m = [], 1
        while m <= cap:
            out.append(m)
            m *= 2
        return out or [1]

    def _generate_experiments(self, zero_stage: int) -> List[Experiment]:
        """(ref: autotuner.py:287) one experiment per admissible micro
        batch at this stage; global batch fixed → gas = global/(mbs*dp)."""
        dp = max(1, len(jax.devices()))
        exps = []
        global_bs = self.base_config.get("train_batch_size",
                                         self.max_train_batch_size or dp)
        for mbs in self._micro_batch_candidates(zero_stage):
            if (global_bs % (mbs * dp)) != 0:
                continue
            overrides = deep_update(
                DEFAULT_TUNING_SPACES[zero_stage],
                {"train_micro_batch_size_per_gpu": mbs,
                 "gradient_accumulation_steps": global_bs // (mbs * dp),
                 "train_batch_size": global_bs})
            cfg = deep_update(self.base_config, overrides)
            cfg.pop(AUTOTUNING, None)
            exps.append(Experiment(canonical_name(cfg), cfg))
        return exps

    # -- experiment execution -----------------------------------------

    def run_ds_config(self, ds_config: Dict) -> float:
        """(ref: autotuner.py:1073) build an engine, run num_tuning_steps
        timed steps, return the metric (higher = better)."""
        import deepspeed_tpu
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=self.loss_fn, model_parameters=self.params,
            config=dict(ds_config))
        batch = self.make_batch(engine.train_batch_size)
        m = engine.train_batch(batch)  # compile + warmup
        jax.block_until_ready(m["loss"])  # drain warmup before timing
        t0 = time.perf_counter()
        for _ in range(self.num_steps):
            m = engine.train_batch(batch)
        jax.block_until_ready(m["loss"])
        dt = (time.perf_counter() - t0) / self.num_steps
        if self.metric == METRIC_LATENCY:
            return -dt
        return engine.train_batch_size / dt  # throughput (also FLOPS proxy)

    def _make_tuner(self, exps: List[Experiment],
                    rm: ResourceManager) -> BaseTuner:
        if self.tuner_type == "gridsearch":
            return GridSearchTuner(exps, rm, self.metric)
        if self.tuner_type == "random":
            return RandomTuner(exps, rm, self.metric)
        return ModelBasedTuner(exps, rm, self.metric)

    # -- main ----------------------------------------------------------

    def tune(self) -> Optional[Dict]:
        """(ref: autotuner.py:396) returns the best full ds_config."""
        self.model_info_profile_run()
        hbm = self.get_gpu_memory_info()
        rm = ResourceManager(self.run_ds_config, results_dir=self.results_dir)

        from deepspeed_tpu.utils.hbm import DEFAULT_HEADROOM_GIB, GiB
        limit = hbm - DEFAULT_HEADROOM_GIB * GiB
        for stage in self.zero_stages:
            inst = self.get_instantiation_memory_required_per_gpu(stage)
            if inst > limit:
                logger.info(f"pruned zero stage {stage}: needs "
                            f"{inst / 1e9:.1f} GB > {limit / 1e9:.1f} GB "
                            f"compile-safe HBM")
                continue
            exps = self._generate_experiments(stage)
            if not exps:
                continue
            tuner = self._make_tuner(exps, rm)
            start = len(rm.finished_experiments)
            n = tuner.tune(sample_size=1, n_trials=self.tuner_num_trials,
                           early_stopping=self.tuner_early_stopping)
            self.records[f"z{stage}"] = [
                e.as_record() for e in rm.finished_experiments[start:]]
            logger.info(f"stage {stage}: ran {n} experiments; best so far "
                        f"{tuner.best_metric_val}")

        best = rm.best()
        self._best_exp = best
        if best is None:
            logger.warning("autotuning found no runnable config")
            return None
        if self.results_dir:
            os.makedirs(self.results_dir, exist_ok=True)
            with open(os.path.join(self.results_dir, "ds_config_optimal.json"),
                      "w") as f:
                json.dump(best.ds_config, f, indent=2)
        logger.info(f"optimal config: {best.name} "
                    f"({self.metric}={best.metric_val:.2f})")
        return best.ds_config

    def print_tuning_results(self) -> None:
        """(ref: autotuner.py:74)"""
        for space, records in self.records.items():
            for r in records:
                logger.info(f"{space} {r['name']}: {r['metric_val']}")
        if self._best_exp:
            logger.info(f"best: {self._best_exp.name} = "
                        f"{self._best_exp.metric_val}")
