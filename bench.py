"""Benchmark: GPT training throughput (tokens/sec/chip) on the local device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric (the BASELINE.json north star): GPT-2 **1.5B**
(48 layers / 1600 hidden / seq 1024 — the reference's own perf-harness
config, ref tests/model/Megatron_GPT2/run_perf_baseline.py:17) training
tokens/sec on ONE chip. The full training state (bf16 params + bf16 Adam
moments with stochastic-rounding updates, bf16.memory_efficient) lives
on-device — 9.3GB of state on a 16GB v5e.

vs_baseline: achieved model-flops utilization / 0.40 — the "A100 MFU
parity" bar from BASELINE.md. MFU uses Megatron-style flops accounting
(6*N_matmul + attention, logit layer included; gpt.train_flops_per_token).

Secondary (detail): gpt2-medium ZeRO-1 fp32-master number — same config
as round 1, for cross-round comparability.
"""

import json
import os
import sys
import time

import jax

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request

honor_platform_request()   # make JAX_PLATFORMS=cpu work despite sitecustomize

import jax.numpy as jnp
import numpy as np

# per-chip bf16 peak FLOPS by device kind
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
    "cpu": 1e12,
}
MFU_BAR = 0.40  # A100-parity bar (see BASELINE.md north star)


def peak_flops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12


def _on_tpu() -> bool:
    d = jax.devices()[0]
    return "tpu" in (d.platform + d.device_kind).lower()


def run_config(preset, batch, seq, steps, ds_overrides, on_tpu,
               flash_block=1024, remat_pol="selective", loss_chunk=0,
               remat=True, flash_block_kv=None,
               bwd_block_q=None, bwd_block_kv=None):
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    cfg = gpt.preset(preset, max_seq_len=seq, dtype=jnp.bfloat16,
                     remat=remat, remat_policy=remat_pol,
                     use_flash_attention=on_tpu,
                     flash_block_q=flash_block,
                     flash_block_kv=flash_block_kv or flash_block,
                     flash_block_bwd_q=bwd_block_q,
                     flash_block_bwd_kv=bwd_block_kv,
                     loss_chunk=loss_chunk)
    if on_tpu:
        # refuse borderline-HBM compiles — they wedge this backend's
        # remote compile service (utils/hbm.py, PERF.md incident log)
        from deepspeed_tpu.utils import hbm as hbm_guard
        hbm_guard.guard_gpt_config(
            cfg, batch, seq,
            precision="bf16" if ds_overrides.get("bf16", {}).get(
                "enabled", True) else "fp32",
            memory_efficient=ds_overrides.get("bf16", {}).get(
                "memory_efficient", False))
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_config = {
        "train_batch_size": batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.1}},
        "steps_per_print": 10_000,
    }
    for k, v in ds_overrides.items():
        if isinstance(v, dict):
            ds_config.setdefault(k, {}).update(v)
        else:
            ds_config[k] = v
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config)
    del params

    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    data = {"tokens": tokens}

    # warmup / compile — block so compile cost stays out of the timed loop
    jax.block_until_ready(engine.train_batch(data)["loss"])
    # per-step sync + median: async windows on a time-shared rig inflate
    # throughput (queue transients) and single outliers (tenancy) deflate
    # it; the median of fully-synced steps is robust to both
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        m = engine.train_batch(data)
        float(m["loss"])
        times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[len(times) // 2]

    tps = batch * seq / dt
    mfu = tps * gpt.train_flops_per_token(cfg, seq) / peak_flops()
    del engine
    return dt, tps, mfu


def _sub(which):
    """Run one bench config in a FRESH subprocess (the remote compile
    helper on this rig can 500 on repeat compiles in one long process)
    and parse its JSON line. Returns None (with a stderr note) on any
    failure so the caller can fall back in-process."""
    import subprocess
    try:
        r = subprocess.run([sys.executable, __file__, "--one", which],
                           capture_output=True, text=True, timeout=1800)
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        print(f"bench subprocess {which!r} rc={r.returncode}: "
              f"{r.stderr[-300:]}", file=sys.stderr)
    except Exception as e:
        print(f"bench subprocess {which!r} failed: {e!r}", file=sys.stderr)
    return None


HEADLINE_OVERRIDE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_HEADLINE.json")


def _headline_overrides() -> dict:
    """Optional repo-root BENCH_HEADLINE.json selecting the probe-winning
    headline variant ({batch, remat_pol, flash_block, flash_block_kv,
    bwd_block_q, bwd_block_kv, loss_chunk}) — when tools/headline_probe.py
    finds a faster configuration, flipping the driver headline to it is a
    one-line data change, not bench-code surgery. Absent file = the
    established b16-full-ce config."""
    try:
        with open(HEADLINE_OVERRIDE) as f:
            return json.load(f)
    except OSError:
        return {}                       # absent: the established config
    except ValueError as e:
        # a BROKEN override must not silently publish the wrong config
        # as the headline — shout and fall back
        print(f"bench: BENCH_HEADLINE.json is malformed ({e}); "
              f"falling back to the default headline config",
              file=sys.stderr)
        return {}


def _run_one(which):
    on_tpu = _on_tpu()
    if which == "headline":
        preset = "gpt2-1.5b" if on_tpu else "gpt2-small"
        ov = _headline_overrides() if on_tpu else {}
        batch, seq = (ov.get("batch", 16), 1024) if on_tpu else (2, 128)
        remat_pol = ov.get("remat_pol", "full")
        loss_chunk = ov.get("loss_chunk", 2048) if on_tpu else 0
        dt, tps, mfu = run_config(
            preset, batch, seq, 10 if on_tpu else 2,
            {"bf16": {"enabled": True, "memory_efficient": True},
             "zero_optimization": {"stage": 3}},
            on_tpu, remat_pol=remat_pol,
            flash_block=ov.get("flash_block", 1024),
            flash_block_kv=ov.get("flash_block_kv"),
            bwd_block_q=ov.get("bwd_block_q"),
            bwd_block_kv=ov.get("bwd_block_kv"),
            loss_chunk=loss_chunk)
        # echo the ACTUAL config so the published label can't drift
        return {"preset": preset, "batch": batch, "seq": seq,
                "dt": dt, "tps": tps, "mfu": mfu,
                "remat_pol": remat_pol, "loss_chunk": loss_chunk}
    if which == "medium":
        preset = "gpt2-medium" if on_tpu else "gpt2-small"
        batch, seq = (8, 1024) if on_tpu else (2, 128)
        dt, tps, mfu = run_config(preset, batch, seq,
                                  20 if on_tpu else 2,
                                  {"zero_optimization": {"stage": 1}},
                                  on_tpu, flash_block=1024)
        return {"preset": preset, "dt": dt, "tps": tps, "mfu": mfu}
    if which == "bert":
        from tools.bert_bench import run as bert_run
        _, sps, tf = bert_run(512, 32, 8)
        return {"samples_per_sec": round(sps, 1),
                "model_tflops": round(tf, 1),
                "vs_reference_v100": round(sps / 52.0, 2)}
    raise ValueError(which)


def _backend_reachable(timeout=240) -> bool:
    """Probe the accelerator backend in a SUBPROCESS: a wedged TPU tunnel
    hangs jax.devices() forever (observed on this rig, PERF.md), and a
    hang inside the driver's bench run would record nothing at all."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return True          # a local CPU backend cannot be unreachable
    import subprocess
    probe = ("import sys; sys.path.insert(0, '.')\n"
             "from deepspeed_tpu.utils import honor_platform_request\n"
             "honor_platform_request()\n"
             "import jax; print(jax.devices())\n")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, timeout=timeout)
        return r.returncode == 0
    except Exception:
        return False


def _wait_for_backend() -> bool:
    """Bounded recovery loop: a transient tunnel wedge must not forfeit
    the round's number (round 2 recorded literal 0 because the probe gave
    up after one attempt — VERDICT r2). Retries with backoff across the
    capture window; total budget via BENCH_RECOVERY_MINUTES (default 25,
    0 = single probe)."""
    budget_s = float(os.environ.get("BENCH_RECOVERY_MINUTES", "25")) * 60
    deadline = time.time() + budget_s
    delay = 60
    attempt = 0
    while True:
        attempt += 1
        if _backend_reachable():
            return True
        if time.time() + delay >= deadline:
            print(f"bench: backend unreachable after {attempt} probes",
                  file=sys.stderr)
            return False
        print(f"bench: backend probe {attempt} failed, retrying in "
              f"{delay}s", file=sys.stderr)
        time.sleep(delay)
        delay = min(delay * 2, 480)


LASTGOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_LASTGOOD.json")


def _save_lastgood(line: dict) -> None:
    try:
        with open(LASTGOOD_PATH, "w") as f:
            json.dump(line, f)
    except OSError as e:
        print(f"bench: could not persist last-good line: {e}",
              file=sys.stderr)


def _emit_unreachable() -> None:
    """Outage path: re-emit the last MEASURED headline with an explicit
    stale marker — an unreachable backend is not zero capability, and a
    consumer reading only value/vs_baseline must still be able to tell
    outage from regression (hence the top-level status field)."""
    err = ("accelerator backend unreachable (device probe hung/failed "
           "across the bounded recovery window); see PERF.md for "
           "measurement provenance")
    try:
        with open(LASTGOOD_PATH) as f:
            last = json.load(f)
    except (OSError, ValueError):
        last = None
    if last is None:
        print(json.dumps({
            "metric": "gpt2_1.5b_seq1024_train_tokens_per_sec_per_chip",
            "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
            "status": "error:backend_unreachable",
            "detail": {"error": err}}))
        return
    out = dict(last)
    out["stale"] = True
    out["status"] = "stale:backend_unreachable"
    detail = dict(out.get("detail") or {})
    detail["stale_reason"] = err
    detail["measured_at"] = last.get("measured_at", "unknown")
    out["detail"] = detail
    print(json.dumps(out))


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        print(json.dumps(_run_one(sys.argv[2])))
        return

    if not _wait_for_backend():
        _emit_unreachable()
        return

    on_tpu = _on_tpu()
    dev = jax.devices()[0].device_kind

    # --- headline: GPT-2 1.5B, full training state on one chip --------
    # (off-TPU the bench is a smoke test — small preset)
    h = _sub("headline") or _run_one("headline")
    headline_preset, batch15, seq = h["preset"], h["batch"], h["seq"]
    dt15, tps15, mfu15 = h["dt"], h["tps"], h["mfu"]

    # --- secondary: gpt2-medium ZeRO-1 (round-1 comparable) -----------
    m = _sub("medium") or _run_one("medium")
    dt_m, tps_m, mfu_m = m["dt"], m["tps"], m["mfu"]

    # --- BERT-large seq512: the reference's own V100 headline ---------
    # (ref docs/_tutorials/bert-pretraining.md:388 — 52 samples/s,
    # 53 TFLOPS on 1x V100)
    bert_detail = None
    if on_tpu:
        try:
            bert_detail = _sub("bert") or _run_one("bert")
        except Exception as e:  # never fail the headline on the extra run
            bert_detail = {"error": repr(e)[:120]}

    line = {
        "metric": f"{headline_preset.replace('-', '_')}"
                  f"_seq{seq}_train_tokens_per_sec_per_chip",
        "value": round(tps15, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu15 / MFU_BAR, 3),
        "detail": {
            "headline": {
                "model": headline_preset +
                         (" (48L/1600h, ref run_perf_baseline.py:17)"
                          if headline_preset == "gpt2-1.5b"
                          else " (off-TPU smoke fallback)"),
                "batch": batch15, "seq": seq,
                "step_ms": round(dt15 * 1e3, 2),
                "mfu": round(mfu15, 4),
                # label echoes what _run_one ACTUALLY ran (incl. any
                # BENCH_HEADLINE.json override) — never re-derived
                "mode": ("bf16 memory_efficient (bf16 params+moments, "
                         "stochastic rounding), zero_stage=3, "
                         f"{h.get('remat_pol', 'full')} remat, "
                         "flash attention, "
                         + ("chunked CE" if h.get("loss_chunk")
                            else "dense CE")),
            },
            "secondary_gpt2_medium": {
                "tokens_per_sec": round(tps_m, 1),
                "step_ms": round(dt_m * 1e3, 2),
                "mfu": round(mfu_m, 4),
                "zero_stage": 1,
            },
            "bert_large_seq512_vs_ref_headline": bert_detail,
            "param_capacity": "see tools/capacity_demo.py — ZeRO-Infinity "
                              "param streaming trains >HBM models "
                              "(PERF.md records the 4B+ runs)",
            "device": dev,
            "flops_accounting": "Megatron-style 6*N_matmul+attn "
                                "(logit layer included)",
        },
    }
    if on_tpu and tps15 > 0:
        saved = dict(line, measured_at=time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        _save_lastgood(saved)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
