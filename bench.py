"""Benchmark: GPT training throughput (tokens/sec/chip) on the local device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: per-chip training throughput on a GPT-2-class model via the full
deepspeed_tpu engine (bf16, ZeRO, remat, flash attention).

vs_baseline: achieved model-flops utilization divided by 0.40 — the "A100
MFU parity" bar from BASELINE.md (the reference's north star is GPT-2
training at >= A100 MFU; 40% MFU is the strong published A100 baseline for
GPT-scale pretraining at this size class). vs_baseline >= 1.0 means we meet
the bar on this chip.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

# per-chip bf16 peak FLOPS by device kind
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
    "cpu": 1e12,
}
MFU_BAR = 0.40  # A100-parity bar (see BASELINE.md north star)


def peak_flops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    # largest GPT-2 family member that trains comfortably on one 16GB chip
    cfg = gpt.preset("gpt2-medium", max_seq_len=1024, dtype=jnp.bfloat16,
                     remat=True, use_flash_attention=on_tpu,
                     flash_block_q=512, flash_block_kv=512)
    batch, seq = (8, 1024) if on_tpu else (2, 256)

    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_config = {
        "train_batch_size": batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.1}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config)

    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    data = {"tokens": tokens}

    # warmup / compile — block on the result so compile+run cost stays out
    # of the timed loop
    jax.block_until_ready(engine.train_batch(data))

    steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(data)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = batch * seq
    tps = tokens_per_step / dt
    flops_per_token = gpt.train_flops_per_token(cfg, seq)
    mfu = tps * flops_per_token / peak_flops()

    print(json.dumps({
        "metric": "gpt2_medium_seq1024_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / MFU_BAR, 3),
        "detail": {
            "model": "gpt2-medium(355M)",
            "batch": batch, "seq": seq,
            "step_ms": round(dt * 1e3, 2),
            "mfu": round(mfu, 4),
            "device": jax.devices()[0].device_kind,
            "zero_stage": 1, "precision": "bf16",
            "flash_attention": on_tpu,
        },
    }))


if __name__ == "__main__":
    main()
