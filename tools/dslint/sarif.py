"""SARIF 2.1.0 emitter for dslint findings.

Emits the minimal valid static-analysis log CI viewers (GitHub code
scanning, VS Code SARIF viewer) consume: one run, one ``tool.driver``
carrying the rule catalog, one ``result`` per finding. New findings are
``error`` level; baselined ones are ``note`` (visible debt, non-
blocking). Paths are repo-root-relative with an ``originalUriBaseIds``
anchor so the log is portable across checkouts.
"""

import json
from typing import Dict, List, Optional, Sequence

from tools.dslint.core import REPO_ROOT, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _help_anchor(rule_id: str) -> str:
    """LINT.md section anchor for a rule id — SARIF viewers surface it
    as the rule's documentation link."""
    n = int(rule_id[2:])
    if n >= 15:
        return "#the-flow-sensitive-rules-phase-3"
    if n >= 11:
        return "#the-interprocedural-rules-phase-2"
    return "#the-rules"


def _rule_entry(rule: Dict[str, str]) -> Dict:
    return {
        "id": rule["id"],
        "name": rule["name"],
        "shortDescription": {"text": rule["name"]},
        "fullDescription": {"text": rule["rationale"]},
        "helpUri": ((REPO_ROOT / "docs" / "LINT.md").as_uri()
                    + _help_anchor(rule["id"])),
        "defaultConfiguration": {"level": "error"},
    }


def _result(f: Finding, rule_index: Dict[str, int]) -> Dict:
    res = {
        "ruleId": f.rule,
        "level": "note" if f.baselined else "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path,
                    "uriBaseId": "REPO_ROOT",
                },
                "region": {
                    "startLine": max(1, int(f.line)),
                    "startColumn": max(1, int(f.col) + 1),
                },
            },
        }],
    }
    if f.rule in rule_index:
        res["ruleIndex"] = rule_index[f.rule]
    if f.snippet:
        loc = res["locations"][0]["physicalLocation"]
        loc["region"]["snippet"] = {"text": f.snippet}
    return res


def to_sarif(new: Sequence[Finding], baselined: Sequence[Finding] = (),
             rules: Optional[Sequence[Dict[str, str]]] = None) -> Dict:
    """The SARIF log as a plain dict; ``rules`` is the combined catalog
    (per-file + interprocedural) as produced by ``rule_catalog()`` /
    ``interproc_catalog()``."""
    if rules is None:
        from tools.dslint.interproc import interproc_catalog
        from tools.dslint.rules import rule_catalog
        rules = rule_catalog() + interproc_catalog()
    rule_index = {r["id"]: i for i, r in enumerate(rules)}
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dslint",
                    "informationUri":
                        (REPO_ROOT / "docs" / "LINT.md").as_uri(),
                    "rules": [_rule_entry(r) for r in rules],
                },
            },
            "originalUriBaseIds": {
                "REPO_ROOT": {"uri": REPO_ROOT.as_uri() + "/"},
            },
            "results": ([_result(f, rule_index) for f in new]
                        + [_result(f, rule_index) for f in baselined]),
        }],
    }


def write_sarif(path, new: Sequence[Finding],
                baselined: Sequence[Finding] = (),
                rules: Optional[Sequence[Dict[str, str]]] = None) -> None:
    log = to_sarif(new, baselined, rules)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(log, fh, indent=1)
        fh.write("\n")
