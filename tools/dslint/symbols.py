"""dslint phase 1: the package-wide symbol table and call graph.

Everything the interprocedural rules (DS011–DS014, :mod:`interproc`)
need to see *across* files is collected here in one pass per module:

- function/method definitions with their parameter lists;
- jit-wrapped callables (``x = jax.jit(fn, donate_argnums=...)``,
  ``@partial(jax.jit, ...)`` decorations) with their donated/static
  positions, keyed the same way call sites spell them — ``("name", x)``
  module-scoped, ``("attr", x)`` package-wide for ``self.x``/``cls.x``;
- fault-site activity: ``fire("site")``/``maybe_fire("site")`` string
  literals, *fire-forwarding* helpers (a function that passes one of
  its own parameters into a fire call — ``serving._device_call``,
  ``paged_cache._fire``), ``KNOWN_SITES`` set literals and
  ``register_site("...")`` calls;
- env-flag activity: literal ``DS_*`` reads (``os.environ[...]``,
  ``os.environ.get``, ``os.getenv``, ``<mapping>.get("DS_...")``),
  ``resolve_flag("DS_...")`` calls, and the declared ``FLAGS`` table
  (name, kind, default) parsed from its AST literal;
- telemetry registrations: ``<metrics>.counter/gauge/histogram(name)``
  and ``<tracer>.event(name)`` calls, with f-string names resolved by
  expanding module-level constant tables (the ``for key, ... in
  _STAT_FIELDS`` / ``for ph in PHASES`` idioms) and degraded to ``*``
  wildcard patterns when a piece stays dynamic;
- a file-level import graph (who imports whom inside the analyzed
  roots), which ``--closure`` uses to lint a changed file plus its
  direct callers.

The jit wrapper spellings come from
``deepspeed_tpu/utils/jit_registry.py`` — loaded straight from the file
path so dslint keeps its never-imports-the-code-under-analysis property
(the module is pure stdlib by contract).
"""

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.dslint.core import REPO_ROOT, link_parents

FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

# -- shared jit-entry-point definition ----------------------------------

_FALLBACK_JIT_CHAINS = (("jax", "jit"), ("jit",), ("jax", "pjit"), ("pjit",))


def _load_jit_chains() -> Tuple[Tuple[str, ...], ...]:
    """The wrapper name-chains from utils/jit_registry.py, loaded from
    the FILE (never via the deepspeed_tpu package, which imports jax).
    Falls back to the built-in list when the file is absent (fixture
    trees)."""
    path = REPO_ROOT / "deepspeed_tpu" / "utils" / "jit_registry.py"
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_ds_jit_registry",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return tuple(tuple(c) for c in mod.JIT_WRAPPER_CHAINS)
    except Exception:
        return _FALLBACK_JIT_CHAINS


JIT_CHAINS = _load_jit_chains()


def _dotted(func: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return list(reversed(parts))
    return []


def _is_jit(func: ast.AST) -> bool:
    return tuple(_dotted(func)) in JIT_CHAINS


def _int_items(value: ast.AST) -> List[int]:
    items = value.elts if isinstance(value, (ast.Tuple, ast.List)) \
        else [value]
    return [i.value for i in items
            if isinstance(i, ast.Constant) and isinstance(i.value, int)]


def _callee_key(node: ast.AST) -> Optional[Tuple[str, str]]:
    """("name", x) for a bare call target, ("attr", x) for self.x/cls.x."""
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return ("attr", node.attr)
    return None


# -- collected records --------------------------------------------------

@dataclass
class JitEntry:
    """One donating/static-carrying jit registration."""
    key: Tuple[str, str]        # how call sites spell it
    path: str
    line: int
    donate: List[int]           # donated positions AS SEEN AT CALL SITES
    static: List[int]
    helper_of: Optional[Tuple[str, str]] = None   # set for propagated entries


@dataclass
class FireSite:
    site: str                   # the literal (or "<dynamic>")
    path: str
    line: int
    fn: Optional[str]           # enclosing function name


@dataclass
class EnvRead:
    var: str
    path: str
    line: int
    how: str                    # "environ" | "getenv" | "get" | "resolve_flag"


@dataclass
class MetricReg:
    name: str                   # concrete name, or wildcard pattern with '*'
    kind: str                   # counter|gauge|histogram|event
    path: str
    line: int
    pattern: bool = False


@dataclass
class FuncInfo:
    name: str
    path: str
    line: int
    params: List[str]
    is_method: bool
    node: ast.AST = field(repr=False, default=None)


@dataclass
class SymbolTable:
    files: List[Tuple[str, ast.AST, Sequence[str]]] = field(
        default_factory=list)
    functions: List[FuncInfo] = field(default_factory=list)
    jit_entries: List[JitEntry] = field(default_factory=list)
    fire_sites: List[FireSite] = field(default_factory=list)
    # (path, fn-name) -> index of the forwarded site parameter (call-site
    # positions: `self` already dropped for methods)
    fire_forwarders: Dict[Tuple[str, str], int] = field(default_factory=dict)
    known_sites: Set[str] = field(default_factory=set)
    known_sites_loc: Optional[Tuple[str, int]] = None
    registered_sites: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    env_reads: List[EnvRead] = field(default_factory=list)
    flags_declared: Dict[str, Tuple[str, object, str, int]] = field(
        default_factory=dict)       # name -> (kind, default, path, line)
    flags_path: Optional[str] = None
    metric_regs: List[MetricReg] = field(default_factory=list)
    imports: Dict[str, Set[str]] = field(default_factory=dict)  # path->paths


# -- per-module collection ----------------------------------------------

_REGISTRY_RECV = ("metrics", "registry", "reg")
_METRIC_METHODS = ("counter", "gauge", "histogram")


class _ModuleCollector:
    """One pass over one module's AST, appending into the SymbolTable."""

    def __init__(self, table: SymbolTable, path: str, tree: ast.AST,
                 lines: Sequence[str]):
        self.t = table
        self.path = path
        self.tree = tree
        self.lines = lines
        # one walk, shared by every collector below — ast.walk per
        # collector dominated the whole lint's runtime before this
        self.nodes: List[ast.AST] = list(ast.walk(tree))
        self.calls: List[ast.Call] = [n for n in self.nodes
                                      if isinstance(n, ast.Call)]
        self.assigns: List[ast.Assign] = [n for n in self.nodes
                                          if isinstance(n, ast.Assign)]
        # name -> registry-method kind, for the `make = metrics.counter
        # if ... else metrics.gauge; make(f"...")` idiom (resolved once
        # per module instead of re-walking the scope per call)
        self.name_reg_kinds: Dict[str, str] = {}
        for a in self.assigns:
            tnames = [t.id for t in a.targets if isinstance(t, ast.Name)]
            if not tnames:
                continue
            attrs = {sub.attr for sub in ast.walk(a.value)
                     if isinstance(sub, ast.Attribute)}
            hit = attrs & set(_METRIC_METHODS)
            if hit:
                for tn in tnames:
                    self.name_reg_kinds[tn] = sorted(hit)[0]
        # module-level constant tables for f-string loop resolution:
        # NAME -> set of strings (tuple-of-str, tuple-of-tuples first
        # elements, dict keys)
        self.const_tables: Dict[str, Set[str]] = {}
        # NAME -> str for simple module-level string constants
        self.str_consts: Dict[str, str] = {}

    # .. module constants ..............................................

    def _collect_consts(self) -> None:
        for node in self.tree.body if hasattr(self.tree, "body") else []:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                v = node.value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    self.str_consts[tgt.id] = v.value
        # second pass so dict keys can reference str constants above
        for node in self.tree.body if hasattr(self.tree, "body") else []:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                strs = self._string_set(node.value)
                if strs:
                    self.const_tables[tgt.id] = strs

    def _string_set(self, v: ast.AST) -> Set[str]:
        """The strings a module-level table yields when iterated: a
        tuple/list/set of strings, a tuple of tuples (first elements),
        or a dict (its keys) — covering ``for ph in PHASES``,
        ``for key, ... in _STAT_FIELDS`` and ``for s in HEALTH_CODES``."""
        out: Set[str] = set()
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
                elif isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                    first = e.elts[0]
                    if isinstance(first, ast.Constant) \
                            and isinstance(first.value, str):
                        out.add(first.value)
        elif isinstance(v, ast.Dict):
            for k in v.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.add(k.value)
                elif isinstance(k, ast.Name) and k.id in self.str_consts:
                    out.add(self.str_consts[k.id])
        return out

    # .. driver ........................................................

    def run(self) -> None:
        self._collect_consts()
        self._collect_functions()
        self._collect_jit_entries()
        self._collect_fault_symbols()
        self._collect_env_reads()
        self._collect_flags_table()
        self._collect_metric_regs()

    # .. functions ......................................................

    def _collect_functions(self) -> None:
        for node in self.nodes:
            if not isinstance(node, FUNC_TYPES):
                continue
            params = [a.arg for a in (list(node.args.posonlyargs)
                                      + list(node.args.args))]
            is_method = bool(params) and params[0] in ("self", "cls")
            self.t.functions.append(FuncInfo(
                name=node.name, path=self.path, line=node.lineno,
                params=params, is_method=is_method, node=node))

    # .. jit entries ....................................................

    def _jit_decorator(self, dec: ast.AST) -> Optional[ast.Call]:
        if isinstance(dec, ast.Call):
            if _is_jit(dec.func):
                return dec
            chain = _dotted(dec.func)
            if chain[-1:] == ["partial"] and dec.args \
                    and _is_jit(dec.args[0]):
                return dec
        return None

    def _collect_jit_entries(self) -> None:
        for node in self.nodes:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                if not _is_jit(call.func):
                    continue
                donate, static = self._donate_static(call)
                if not donate:
                    continue
                for tgt in node.targets:
                    key = _callee_key(tgt)
                    if key is None and isinstance(tgt, ast.Attribute):
                        # module-attr targets (rare) — track by attr name
                        key = ("attr", tgt.attr)
                    if key is not None:
                        # jitting a bound method (jax.jit(self._fn)) drops
                        # `self`, so the positions apply at call sites as-is
                        self.t.jit_entries.append(JitEntry(
                            key=key, path=self.path, line=node.lineno,
                            donate=donate, static=static))
            elif isinstance(node, FUNC_TYPES):
                for dec in node.decorator_list:
                    jd = self._jit_decorator(dec)
                    if jd is None:
                        continue
                    donate, static = self._donate_static(jd)
                    if not donate:
                        continue
                    params = [a.arg for a in (list(node.args.posonlyargs)
                                              + list(node.args.args))]
                    is_method = bool(params) and params[0] in ("self", "cls")
                    # a decorated method's donate positions count `self`;
                    # self.x call sites don't pass it — shift by one
                    off = 1 if is_method else 0
                    key = ("attr" if is_method else "name", node.name)
                    self.t.jit_entries.append(JitEntry(
                        key=key, path=self.path, line=node.lineno,
                        donate=[p - off for p in donate if p - off >= 0],
                        static=[p - off for p in static if p - off >= 0]))
                    break

    @staticmethod
    def _donate_static(call: ast.Call) -> Tuple[List[int], List[int]]:
        donate: List[int] = []
        static: List[int] = []
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donate = _int_items(kw.value)
            elif kw.arg == "static_argnums":
                static = _int_items(kw.value)
        return donate, static

    # .. fault sites ....................................................

    def _fire_call_site_arg(self, call: ast.Call) -> Optional[ast.AST]:
        """The site argument when ``call`` is a fire: ``fire(x)`` /
        ``maybe_fire(x)`` / ``<anything>.fire(x)`` / ``<anything>.
        maybe_fire(x)``."""
        chain = _dotted(call.func)
        if chain and chain[-1] in ("fire", "maybe_fire") and call.args:
            return call.args[0]
        return None

    def _collect_fault_symbols(self) -> None:
        # KNOWN_SITES / register_site literals
        for node in self.assigns:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id == "KNOWN_SITES" \
                        and isinstance(node.value, (ast.Set, ast.Tuple,
                                                    ast.List)):
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            self.t.known_sites.add(e.value)
                    self.t.known_sites_loc = (self.path, node.lineno)
        for node in self.calls:
            chain = _dotted(node.func)
            if chain[-1:] == ["register_site"] and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                self.t.registered_sites[node.args[0].value] = (
                    self.path, node.lineno)
            # fired literals + fire-forwarding helpers: a fire literal is
            # attributed to EVERY enclosing function (a nested closure's
            # fire still covers its public host for DS012)
            arg = self._fire_call_site_arg(node)
            if arg is None:
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                fns = self._enclosing_funcs(node)
                for fn in fns or [None]:
                    self.t.fire_sites.append(FireSite(
                        site=arg.value, path=self.path, line=node.lineno,
                        fn=fn.name if fn is not None else None))
            elif isinstance(arg, ast.Name):
                fn = self._enclosing_func(node)
                if fn is None:
                    continue
                params = [a.arg for a in (list(fn.args.posonlyargs)
                                          + list(fn.args.args))]
                is_method = bool(params) and params[0] in ("self", "cls")
                if arg.id in params:
                    # helper forwards its own param into the fire —
                    # record the call-site position (minus self)
                    idx = params.index(arg.id) - (1 if is_method else 0)
                    if idx >= 0:
                        self.t.fire_forwarders[(self.path, fn.name)] = idx

    def _enclosing_funcs(self, node: ast.AST) -> List[ast.AST]:
        out: List[ast.AST] = []
        p = getattr(node, "_ds_parent", None)
        while p is not None:
            if isinstance(p, FUNC_TYPES):
                out.append(p)
            p = getattr(p, "_ds_parent", None)
        return out

    def _enclosing_func(self, node: ast.AST) -> Optional[ast.AST]:
        p = getattr(node, "_ds_parent", None)
        while p is not None:
            if isinstance(p, FUNC_TYPES):
                return p
            p = getattr(p, "_ds_parent", None)
        return None

    # .. env reads ......................................................

    def _collect_env_reads(self) -> None:
        for node in self.nodes:
            if isinstance(node, ast.Subscript):
                chain = _dotted(node.value)
                if chain == ["os", "environ"] \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    self.t.env_reads.append(EnvRead(
                        node.slice.value, self.path, node.lineno, "environ"))
            if not isinstance(node, ast.Call) or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            chain = _dotted(node.func)
            if chain == ["os", "getenv"]:
                self.t.env_reads.append(EnvRead(
                    first.value, self.path, node.lineno, "getenv"))
            elif chain[-1:] == ["resolve_flag"]:
                self.t.env_reads.append(EnvRead(
                    first.value, self.path, node.lineno, "resolve_flag"))
            elif chain[-1:] == ["get"] and first.value.startswith("DS_"):
                # os.environ.get / env.get(<mapping param>) / dict get of
                # a DS_* key — all count as env-flag reads for DS013
                self.t.env_reads.append(EnvRead(
                    first.value, self.path, node.lineno, "get"))

    # .. FLAGS table ....................................................

    def _collect_flags_table(self) -> None:
        for node in self.assigns:
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "FLAGS" not in names:
                continue
            self.t.flags_path = self.path
            # every Call inside the literal whose first arg is a DS_*
            # string declares a flag: covers Flag("DS_X", kind, default)
            # and the _mk("DS_X", kind, default, help) helper alike
            for call in ast.walk(node.value):
                if not (isinstance(call, ast.Call) and call.args):
                    continue
                a = call.args
                if not (isinstance(a[0], ast.Constant)
                        and isinstance(a[0].value, str)
                        and a[0].value.startswith("DS_")):
                    continue
                kind = a[1].value if len(a) > 1 \
                    and isinstance(a[1], ast.Constant) else "?"
                default = a[2].value if len(a) > 2 \
                    and isinstance(a[2], ast.Constant) else None
                self.t.flags_declared[a[0].value] = (
                    kind, default, self.path, call.lineno)

    # .. telemetry registrations .......................................

    def _collect_metric_regs(self) -> None:
        for node in self.calls:
            if not node.args:
                continue
            kind = self._reg_kind(node)
            if kind is None:
                continue
            name = self._name_of(node.args[0], node)
            if name is None:
                continue
            concrete, pattern = name
            self.t.metric_regs.append(MetricReg(
                name=concrete, kind=kind, path=self.path,
                line=node.lineno, pattern=pattern))

    def _reg_kind(self, call: ast.Call) -> Optional[str]:
        """counter/gauge/histogram/event when ``call`` registers a
        telemetry name; None otherwise (including bare Counter/Gauge/
        Histogram constructors, which never reach a registry)."""
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _METRIC_METHODS:
                recv = _dotted(func.value)
                if recv and (recv[-1] in _REGISTRY_RECV
                             or any(r in _REGISTRY_RECV for r in recv)):
                    return func.attr
                return None
            if func.attr == "event":
                recv = _dotted(func.value)
                if recv and ("tracer" in [r.lower() for r in recv]
                             or recv[-1].lower().endswith("tracer")):
                    return "event"
                return None
            return None
        if isinstance(func, ast.Name):
            if func.id in ("Counter", "Gauge", "Histogram"):
                return None      # constructor, not a registry entry
            # the `make = metrics.counter if ... else metrics.gauge;
            # make(f"...")` idiom: the name was assigned somewhere in
            # this module from an expression mentioning a registry
            # method (precomputed map; conditional counter-or-gauge
            # resolves to the first kind — the schema doesn't key on
            # kind for existence checks)
            return self.name_reg_kinds.get(func.id)
        return None

    def _name_of(self, arg: ast.AST,
                 call: ast.Call) -> Optional[Tuple[str, bool]]:
        """(name, is_pattern) for the registration's name argument:
        literal → concrete; f-string → expanded against loop constant
        tables where possible, else a ``*`` wildcard pattern. Returns a
        '|'-joined set marker via multiple appends instead? No — the
        caller gets ONE entry; expansion appends extra records here."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value, False)
        if not isinstance(arg, ast.JoinedStr):
            return None
        # try to expand each formatted value via loop constant tables
        parts: List[List[str]] = []
        dynamic = False
        for piece in arg.values:
            if isinstance(piece, ast.Constant):
                parts.append([str(piece.value)])
            elif isinstance(piece, ast.FormattedValue) \
                    and isinstance(piece.value, ast.Name):
                vals = self._loop_values(piece.value.id, call)
                if vals:
                    parts.append(sorted(vals))
                else:
                    parts.append(["*"])
                    dynamic = True
            else:
                parts.append(["*"])
                dynamic = True
        if dynamic:
            pat = "".join(p[0] if len(p) == 1 and p[0] != "*" else "*"
                          for p in parts)
            # collapse runs of *
            while "**" in pat:
                pat = pat.replace("**", "*")
            return (pat, True)
        # cartesian expansion (in practice one dynamic piece)
        names = [""]
        for p in parts:
            names = [n + v for n in names for v in p]
        kind = self._reg_kind(call)
        for extra in names[1:]:
            self.t.metric_regs.append(MetricReg(
                name=extra, kind=kind or "counter", path=self.path,
                line=call.lineno, pattern=False))
        return (names[0], False)

    def _loop_values(self, var: str, call: ast.Call) -> Set[str]:
        """Strings ``var`` ranges over, when it is the target (or first
        tuple element) of a for/comprehension iterating a module-level
        constant table — the f-string-in-loop registration idiom."""
        node: ast.AST = call
        p = getattr(node, "_ds_parent", None)
        while p is not None:
            targets_iters: List[Tuple[ast.AST, ast.AST]] = []
            if isinstance(p, (ast.For, ast.AsyncFor)):
                targets_iters.append((p.target, p.iter))
            for gen in getattr(p, "generators", []) or []:
                targets_iters.append((gen.target, gen.iter))
            for tgt, it in targets_iters:
                bound = None
                if isinstance(tgt, ast.Name) and tgt.id == var:
                    bound = True
                elif isinstance(tgt, ast.Tuple) and tgt.elts \
                        and isinstance(tgt.elts[0], ast.Name) \
                        and tgt.elts[0].id == var:
                    bound = True     # `for key, kind, help_ in TABLE`
                if bound:
                    if isinstance(it, ast.Name):
                        vals = self.const_tables.get(it.id, set())
                        if vals:
                            return vals
                    return set()
            p = getattr(p, "_ds_parent", None)
        return set()

    # .. imports (file-level call graph) ................................

    def collect_imports(self, module_index: Dict[str, str]) -> None:
        """Record which analyzed files this module imports.
        ``module_index`` maps dotted module names (``deepspeed_tpu.
        inference.serving``) to analyzed file paths."""
        deps: Set[str] = set()
        for node in self.nodes:
            mods: List[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                mods = [node.module] + [f"{node.module}.{a.name}"
                                        for a in node.names]
            for m in mods:
                if m in module_index and module_index[m] != self.path:
                    deps.add(module_index[m])
        self.t.imports[self.path] = deps


# -- table construction -------------------------------------------------

def module_name_of(path: str) -> Optional[str]:
    """Dotted module name for a repo-relative posix path
    (``deepspeed_tpu/inference/serving.py`` →
    ``deepspeed_tpu.inference.serving``; ``__init__.py`` maps to its
    package)."""
    if not path.endswith(".py"):
        return None
    parts = path[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts:
        return None
    return ".".join(parts)


def build_symbol_table(
        files: Sequence[Tuple[str, ast.AST, Sequence[str]]]) -> SymbolTable:
    """Phase 1: one SymbolTable over every parsed module."""
    table = SymbolTable(files=list(files))
    collectors = []
    module_index: Dict[str, str] = {}
    for path, tree, lines in files:
        link_parents(tree)      # idempotent; collectors walk upward
        mod = module_name_of(path)
        if mod:
            module_index[mod] = path
    for path, tree, lines in files:
        c = _ModuleCollector(table, path, tree, lines)
        c.run()
        c.collect_imports(module_index)
        collectors.append(c)
    # per-function keyed-call lists, computed once and shared by the
    # propagation passes below (re-walking per fixpoint round was the
    # hot spot of the whole lint)
    fn_calls: List[Tuple[FuncInfo, List[Tuple[Tuple[str, str],
                                              ast.Call]]]] = []
    for fn in table.functions:
        if fn.node is None:
            continue
        pairs = []
        for call in ast.walk(fn.node):
            if isinstance(call, ast.Call):
                key = _callee_key(call.func)
                if key is not None:
                    pairs.append((key, call))
        fn_calls.append((fn, pairs))
    _propagate_helper_donation(table, fn_calls)
    _collect_forwarded_fires(table, fn_calls)
    return table


def _collect_forwarded_fires(table: SymbolTable, fn_calls) -> None:
    """A literal passed into a fire-forwarder's site parameter counts as
    fired: ``self._device_call("serving.dispatch", fn, tok)`` fires
    ``serving.dispatch`` even though the ``fire(...)`` call itself only
    sees a variable. Forwarding is transitive — ``_maybe_inject`` passes
    its site into ``_fire`` which passes it into ``faults.fire`` — so
    the forwarder set is closed to a fixpoint first."""
    by_name: Dict[str, int] = {fn: idx for (_, fn), idx
                               in table.fire_forwarders.items()}
    if not by_name:
        return
    changed = True
    while changed:
        changed = False
        for fn, pairs in fn_calls:
            if (fn.path, fn.name) in table.fire_forwarders:
                continue
            off = 1 if fn.is_method else 0
            for key, call in pairs:
                if key[1] not in by_name:
                    continue
                idx = by_name[key[1]]
                if idx < len(call.args) \
                        and isinstance(call.args[idx], ast.Name) \
                        and call.args[idx].id in fn.params:
                    pos = fn.params.index(call.args[idx].id) - off
                    if pos >= 0:
                        table.fire_forwarders[(fn.path, fn.name)] = pos
                        by_name[fn.name] = pos
                        changed = True
                    break
    for path, tree, lines in table.files:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            key = _callee_key(node.func)
            if key is None or key[1] not in by_name:
                continue
            idx = by_name[key[1]]
            if idx < len(node.args) \
                    and isinstance(node.args[idx], ast.Constant) \
                    and isinstance(node.args[idx].value, str):
                fn = None
                p = getattr(node, "_ds_parent", None)
                while p is not None:
                    if isinstance(p, FUNC_TYPES):
                        fn = p.name
                        break
                    p = getattr(p, "_ds_parent", None)
                table.fire_sites.append(FireSite(
                    site=node.args[idx].value, path=path,
                    line=node.lineno, fn=fn))


def _propagate_helper_donation(table: SymbolTable, fn_calls) -> None:
    """One level of helper inlining for DS011: a function that passes
    one of its own parameters into a donated position of a jit entry
    itself donates that parameter — callers of the helper get the same
    use-after check."""
    by_key: Dict[Tuple[str, str], List[JitEntry]] = {}
    for e in table.jit_entries:
        by_key.setdefault(e.key, []).append(e)
    new_entries: List[JitEntry] = []
    for fn, pairs in fn_calls:
        params = fn.params
        is_method = fn.is_method
        donated_params: Set[int] = set()
        for key, call in pairs:
            entries = by_key.get(key)
            if not entries:
                continue
            for entry in entries:
                # name-keyed entries only bind within their own module
                if entry.key[0] == "name" and entry.path != fn.path:
                    continue
                for pos in entry.donate:
                    if pos < len(call.args) \
                            and isinstance(call.args[pos], ast.Name) \
                            and call.args[pos].id in params:
                        donated_params.add(params.index(call.args[pos].id))
        if not donated_params:
            continue
        off = 1 if is_method else 0
        donate = sorted(p - off for p in donated_params if p - off >= 0)
        if not donate:
            continue
        key = ("attr" if is_method else "name", fn.name)
        if any(e.key == key for e in table.jit_entries):
            continue    # already a jit entry under this name
        new_entries.append(JitEntry(
            key=key, path=fn.path, line=fn.line, donate=donate,
            static=[], helper_of=key))
    table.jit_entries.extend(new_entries)


# -- import-graph cache (gate.sh quick / --closure) ---------------------

CALLGRAPH_CACHE = REPO_ROOT / "build" / "dslint_callgraph.json"

# Shared analysis INPUTS whose content changes rule behaviour without
# changing any analyzed .py file's import graph: the jit-wrapper/twin
# spec and the telemetry schema. Their hashes ride the cache so a
# `--closure` run after editing one of them misses the cache and falls
# back to a full pass (a stale cache here means DS002/DS011/DS014/DS015
# silently lint against yesterday's contract).
CACHE_INPUT_FILES: Tuple[Tuple[str, Path], ...] = (
    ("jit_registry", REPO_ROOT / "deepspeed_tpu" / "utils"
     / "jit_registry.py"),
    ("telemetry_schema", REPO_ROOT / "tools" / "dslint"
     / "telemetry_schema.json"),
)


def cache_input_hashes(files: Optional[Sequence[Tuple[str, Path]]] = None
                       ) -> Dict[str, str]:
    """sha256 per shared analysis input; absent files hash to ''."""
    import hashlib
    out: Dict[str, str] = {}
    for key, p in (CACHE_INPUT_FILES if files is None else files):
        try:
            out[key] = hashlib.sha256(Path(p).read_bytes()).hexdigest()
        except OSError:
            out[key] = ""
    return out


def write_callgraph_cache(table: SymbolTable,
                          path: Optional[Path] = None,
                          inputs: Optional[Dict[str, str]] = None) -> Path:
    path = Path(path or CALLGRAPH_CACHE)
    path.parent.mkdir(parents=True, exist_ok=True)
    data = {p: sorted(deps) for p, deps in sorted(table.imports.items())}
    path.write_text(json.dumps({
        "version": 2,
        "inputs": cache_input_hashes() if inputs is None else inputs,
        "imports": data}, indent=1) + "\n", encoding="utf-8")
    return path


def load_callgraph_cache(path: Optional[Path] = None,
                         inputs: Optional[Dict[str, str]] = None
                         ) -> Dict[str, Set[str]]:
    """The cached import graph, or {} when the cache is missing,
    unreadable, from another cache version, or was written against
    different shared-input content (jit_registry / telemetry_schema) —
    {} makes --closure fall back to a full re-analysis."""
    path = Path(path or CALLGRAPH_CACHE)
    if not path.exists():
        return {}
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    if data.get("version") != 2:
        return {}
    current = cache_input_hashes() if inputs is None else inputs
    if data.get("inputs") != current:
        return {}
    return {p: set(deps) for p, deps in data.get("imports", {}).items()}


def closure_of(changed: Sequence[str],
               imports: Dict[str, Set[str]]) -> List[str]:
    """Changed files plus their DIRECT callers (files importing them),
    repo-relative paths in, repo-relative paths out."""
    changed_set = set(changed)
    out = set(changed_set)
    for path, deps in imports.items():
        if deps & changed_set:
            out.add(path)
    return sorted(out)
