"""dslint — JAX/TPU-aware static analysis for this repo.

CLI: ``python -m tools.dslint deepspeed_tpu tools tests`` (see
__main__.py). Library surface (used by tests): analyze_source /
analyze_paths / analyze_package, load_baseline / apply_baseline /
write_baseline, default_rules, interproc_rules, build_symbol_table,
to_sarif — plus the v3 dataflow core: build_cfg, solve_forward,
GenKill, summarize_pairs, dataflow_rules.
"""

from tools.dslint.core import (Finding, analyze_package, analyze_paths,
                               analyze_source, apply_baseline,
                               load_baseline, write_baseline)
from tools.dslint.dataflow import (CFG, Block, ForwardAnalysis, GenKill,
                                   PairSpec, build_cfg,
                                   build_pair_summaries, dataflow_catalog,
                                   dataflow_rules, solve_forward,
                                   summarize_pairs)
from tools.dslint.interproc import interproc_catalog, interproc_rules
from tools.dslint.rules import default_rules, rule_catalog
from tools.dslint.sarif import to_sarif, write_sarif
from tools.dslint.symbols import build_symbol_table

__all__ = ["Finding", "analyze_package", "analyze_paths", "analyze_source",
           "apply_baseline", "load_baseline", "write_baseline",
           "default_rules", "rule_catalog", "interproc_rules",
           "interproc_catalog", "build_symbol_table", "to_sarif",
           "write_sarif", "CFG", "Block", "ForwardAnalysis", "GenKill",
           "PairSpec", "build_cfg", "build_pair_summaries",
           "dataflow_catalog", "dataflow_rules", "solve_forward",
           "summarize_pairs"]
