"""dslint — JAX/TPU-aware static analysis for this repo.

CLI: ``python -m tools.dslint deepspeed_tpu tools`` (see __main__.py).
Library surface (used by tests): analyze_source / analyze_paths,
load_baseline / apply_baseline / write_baseline, default_rules.
"""

from tools.dslint.core import (Finding, analyze_paths, analyze_source,
                               apply_baseline, load_baseline,
                               write_baseline)
from tools.dslint.rules import default_rules, rule_catalog

__all__ = ["Finding", "analyze_paths", "analyze_source", "apply_baseline",
           "load_baseline", "write_baseline", "default_rules",
           "rule_catalog"]
