"""dslint core: findings, suppressions, baseline, and the analysis driver.

The analyzer is pure stdlib ``ast`` — no third-party parser, no imports
of the code under analysis (modules with heavyweight import side effects
lint exactly like everything else). Rules live in
:mod:`tools.dslint.rules`; each has an ID (``DS00x``), an ``autofixable``
flag, and a one-line rationale surfaced by ``--list-rules``.

Suppression syntax (checked per line)::

    x = float(dev_val)        # dslint: disable=DS001 — reason
    # dslint: disable=DS004   (comment-only line: covers the NEXT line)
    # dslint: disable-file=DS005 — whole-file waiver (bootstrap layer)

Baseline: a checked-in JSON multiset of ``(path, rule, stripped source
line)`` triples. Findings that match a baseline entry are reported as
*baselined* (visible debt) but do not fail the run, so the lint can land
strict rules without a big-bang cleanup. ``--update-baseline`` rewrites
the file from the current tree; entries key on line TEXT, not line
numbers, so unrelated edits don't invalidate them.
"""

import ast
import json
import os
from collections import Counter
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import re

# repo root = parents of tools/dslint/; used to normalize finding paths so
# baseline entries are stable regardless of the invocation cwd
REPO_ROOT = Path(__file__).resolve().parents[2]

_SUPPRESS_RE = re.compile(r"#\s*dslint:\s*disable=([A-Z0-9_]+(?:\s*,\s*[A-Z0-9_]+)*)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*dslint:\s*disable-file=([A-Z0-9_]+(?:\s*,\s*[A-Z0-9_]+)*)")


@dataclass
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    baselined: bool = False

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift, line text mostly doesn't."""
        return (self.path, self.rule, self.snippet)

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} {self.message}"


def link_parents(tree: ast.AST) -> ast.AST:
    """Annotate every node with ``_ds_parent`` so rules can walk upward."""
    if getattr(tree, "_ds_linked", False):
        return tree
    tree._ds_linked = True
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._ds_parent = node
    return tree


def parse_suppressions(
        lines: Sequence[str]) -> Tuple[Set[str], Dict[int, Set[str]]]:
    """Returns (file-wide suppressed rules, line -> suppressed rules).

    A trailing comment covers its own line and the next (multi-line
    statements report on their first line); a comment-only line covers
    the next line.
    """
    file_rules: Set[str] = set()
    by_line: Dict[int, Set[str]] = {}
    for i, ln in enumerate(lines, 1):
        m = _SUPPRESS_FILE_RE.search(ln)
        if m:
            file_rules |= {r.strip() for r in m.group(1).split(",")}
        m = _SUPPRESS_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        covers = (i + 1,) if ln.strip().startswith("#") else (i, i + 1)
        for j in covers:
            by_line.setdefault(j, set()).update(rules)
    return file_rules, by_line


def analyze_source(src: str, path: str = "<memory>",
                   rules: Optional[Sequence] = None) -> List[Finding]:
    """Run every rule over one source string. Honors inline suppressions;
    baseline filtering is the caller's job (see :func:`apply_baseline`)."""
    if rules is None:
        from tools.dslint.rules import default_rules
        rules = default_rules()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("DS000", path, int(e.lineno or 0), int(e.offset or 0),
                        f"syntax error: {e.msg}")]
    link_parents(tree)
    lines = src.splitlines()
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(tree, lines, path))
    for f in findings:
        if not f.snippet and 0 < f.line <= len(lines):
            f.snippet = lines[f.line - 1].strip()
    file_sup, line_sup = parse_suppressions(lines)
    findings = [f for f in findings
                if f.rule not in file_sup
                and f.rule not in line_sup.get(f.line, ())]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _norm_path(p: str) -> str:
    """Repo-root-relative posix path when possible (baseline stability)."""
    rp = Path(p).resolve()
    try:
        return rp.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return Path(p).as_posix()


def iter_py_files(paths: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(f for f in pp.rglob("*.py")
                              if not any(part.startswith(".")
                                         or part in ("__pycache__", "build")
                                         for part in f.parts)))
        elif pp.suffix == ".py" and pp.exists():
            out.append(pp)
    # dedupe, keep order
    seen: Set[Path] = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        try:
            src = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("DS000", _norm_path(str(f)), 0, 0,
                                    f"unreadable: {e}"))
            continue
        findings.extend(analyze_source(src, path=_norm_path(str(f)),
                                       rules=rules))
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return findings


def analyze_package(paths: Iterable[str],
                    rules: Optional[Sequence] = None,
                    interproc: Optional[Sequence] = None,
                    docs_root=None,
                    schema_path=None,
                    partial: bool = False,
                    stats: Optional[Dict[str, float]] = None,
                    symtab_out: Optional[list] = None) -> List[Finding]:
    """The two-phase driver: parse every file ONCE, run the per-file
    rules (phase 1 consumers), build the package-wide symbol table, run
    the interprocedural rules (phase 2) over it. Inline suppressions
    cover interprocedural findings exactly like per-file ones.

    ``interproc=None`` runs the full DS011–DS014 set; pass ``[]`` to
    skip phase 2. ``partial=True`` (closure mode) disables the
    whole-tree completeness directions inside the interproc rules.
    ``stats`` (a dict) is filled with phase timings in seconds.
    ``symtab_out`` (a list) receives the built SymbolTable, so callers
    can persist the import graph for ``--closure``.
    """
    import time
    t0 = time.perf_counter()
    if rules is None:
        from tools.dslint.rules import default_rules
        rules = default_rules()
    if interproc is None:
        from tools.dslint.interproc import interproc_rules
        interproc = interproc_rules()

    parsed: List[Tuple[str, ast.AST, List[str]]] = []
    sup: Dict[str, Tuple[Set[str], Dict[int, Set[str]]]] = {}
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        path = _norm_path(str(f))
        try:
            src = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("DS000", path, 0, 0,
                                    f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding("DS000", path, int(e.lineno or 0),
                                    int(e.offset or 0),
                                    f"syntax error: {e.msg}"))
            continue
        link_parents(tree)
        lines = src.splitlines()
        parsed.append((path, tree, lines))
        sup[path] = parse_suppressions(lines)
    if stats is not None:
        stats["parse_s"] = time.perf_counter() - t0

    t1 = time.perf_counter()
    for path, tree, lines in parsed:
        for rule in rules:
            findings.extend(rule.check(tree, lines, path))
    if stats is not None:
        stats["intraproc_s"] = time.perf_counter() - t1

    t2 = time.perf_counter()
    if interproc:
        from tools.dslint.symbols import build_symbol_table
        table = build_symbol_table(parsed)
        if symtab_out is not None:
            symtab_out.append(table)
        for rule in interproc:
            findings.extend(rule.check_package(
                table, docs_root=docs_root, schema_path=schema_path,
                partial=partial))
    elif symtab_out is not None:
        from tools.dslint.symbols import build_symbol_table
        symtab_out.append(build_symbol_table(parsed))
    if stats is not None:
        stats["interproc_s"] = time.perf_counter() - t2

    lines_by_path = {p: ls for p, _, ls in parsed}
    for f in findings:
        ls = lines_by_path.get(f.path)
        if not f.snippet and ls and 0 < f.line <= len(ls):
            f.snippet = ls[f.line - 1].strip()
    out = []
    for f in findings:
        file_sup, line_sup = sup.get(f.path, (set(), {}))
        if f.rule in file_sup or f.rule in line_sup.get(f.line, ()):
            continue
        out.append(f)
    out.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    if stats is not None:
        stats["total_s"] = time.perf_counter() - t0
        stats["files"] = len(parsed)
    return out


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[os.PathLike] = None) -> Counter:
    path = Path(path or DEFAULT_BASELINE)
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text(encoding="utf-8"))
    return Counter((e["path"], e["rule"], e["snippet"])
                   for e in data.get("entries", []))


def write_baseline(findings: Sequence[Finding],
                   path: Optional[os.PathLike] = None) -> Path:
    path = Path(path or DEFAULT_BASELINE)
    entries = [{"path": f.path, "rule": f.rule, "snippet": f.snippet}
               for f in sorted(findings, key=lambda f: f.key())]
    path.write_text(json.dumps({"version": 1, "entries": entries},
                               indent=1) + "\n", encoding="utf-8")
    return path


def apply_baseline(findings: Sequence[Finding],
                   baseline: Counter) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, baselined). Baseline entries are a
    multiset so N identical lines need N entries."""
    budget = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            f.baselined = True
            old.append(f)
        else:
            new.append(f)
    return new, old


def findings_to_json(new: Sequence[Finding],
                     baselined: Sequence[Finding]) -> str:
    return json.dumps({
        "findings": [asdict(f) for f in new],
        "baselined": [asdict(f) for f in baselined],
        "counts": {"new": len(new), "baselined": len(baselined)},
    }, indent=1)
