"""dslint core: findings, suppressions, baseline, and the analysis driver.

The analyzer is pure stdlib ``ast`` — no third-party parser, no imports
of the code under analysis (modules with heavyweight import side effects
lint exactly like everything else). Rules live in
:mod:`tools.dslint.rules`; each has an ID (``DS00x``), an ``autofixable``
flag, and a one-line rationale surfaced by ``--list-rules``.

Suppression syntax (checked per line)::

    x = float(dev_val)        # dslint: disable=DS001 — reason
    # dslint: disable=DS004   (comment-only line: covers the NEXT line)
    # dslint: disable-file=DS005 — whole-file waiver (bootstrap layer)

Baseline: a checked-in JSON multiset of ``(path, rule, stripped source
line)`` triples. Findings that match a baseline entry are reported as
*baselined* (visible debt) but do not fail the run, so the lint can land
strict rules without a big-bang cleanup. ``--update-baseline`` rewrites
the file from the current tree; entries key on line TEXT, not line
numbers, so unrelated edits don't invalidate them.
"""

import ast
import json
import os
from collections import Counter
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import re

# repo root = parents of tools/dslint/; used to normalize finding paths so
# baseline entries are stable regardless of the invocation cwd
REPO_ROOT = Path(__file__).resolve().parents[2]

_SUPPRESS_RE = re.compile(r"#\s*dslint:\s*disable=([A-Z0-9_]+(?:\s*,\s*[A-Z0-9_]+)*)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*dslint:\s*disable-file=([A-Z0-9_]+(?:\s*,\s*[A-Z0-9_]+)*)")


@dataclass
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    baselined: bool = False

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift, line text mostly doesn't."""
        return (self.path, self.rule, self.snippet)

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} {self.message}"


def link_parents(tree: ast.AST) -> ast.AST:
    """Annotate every node with ``_ds_parent`` so rules can walk upward."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._ds_parent = node
    return tree


def parse_suppressions(
        lines: Sequence[str]) -> Tuple[Set[str], Dict[int, Set[str]]]:
    """Returns (file-wide suppressed rules, line -> suppressed rules).

    A trailing comment covers its own line and the next (multi-line
    statements report on their first line); a comment-only line covers
    the next line.
    """
    file_rules: Set[str] = set()
    by_line: Dict[int, Set[str]] = {}
    for i, ln in enumerate(lines, 1):
        m = _SUPPRESS_FILE_RE.search(ln)
        if m:
            file_rules |= {r.strip() for r in m.group(1).split(",")}
        m = _SUPPRESS_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        covers = (i + 1,) if ln.strip().startswith("#") else (i, i + 1)
        for j in covers:
            by_line.setdefault(j, set()).update(rules)
    return file_rules, by_line


def analyze_source(src: str, path: str = "<memory>",
                   rules: Optional[Sequence] = None) -> List[Finding]:
    """Run every rule over one source string. Honors inline suppressions;
    baseline filtering is the caller's job (see :func:`apply_baseline`)."""
    if rules is None:
        from tools.dslint.rules import default_rules
        rules = default_rules()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("DS000", path, int(e.lineno or 0), int(e.offset or 0),
                        f"syntax error: {e.msg}")]
    link_parents(tree)
    lines = src.splitlines()
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(tree, lines, path))
    for f in findings:
        if not f.snippet and 0 < f.line <= len(lines):
            f.snippet = lines[f.line - 1].strip()
    file_sup, line_sup = parse_suppressions(lines)
    findings = [f for f in findings
                if f.rule not in file_sup
                and f.rule not in line_sup.get(f.line, ())]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _norm_path(p: str) -> str:
    """Repo-root-relative posix path when possible (baseline stability)."""
    rp = Path(p).resolve()
    try:
        return rp.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return Path(p).as_posix()


def iter_py_files(paths: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(f for f in pp.rglob("*.py")
                              if not any(part.startswith(".")
                                         or part in ("__pycache__", "build")
                                         for part in f.parts)))
        elif pp.suffix == ".py" and pp.exists():
            out.append(pp)
    # dedupe, keep order
    seen: Set[Path] = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        try:
            src = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("DS000", _norm_path(str(f)), 0, 0,
                                    f"unreadable: {e}"))
            continue
        findings.extend(analyze_source(src, path=_norm_path(str(f)),
                                       rules=rules))
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return findings


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[os.PathLike] = None) -> Counter:
    path = Path(path or DEFAULT_BASELINE)
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text(encoding="utf-8"))
    return Counter((e["path"], e["rule"], e["snippet"])
                   for e in data.get("entries", []))


def write_baseline(findings: Sequence[Finding],
                   path: Optional[os.PathLike] = None) -> Path:
    path = Path(path or DEFAULT_BASELINE)
    entries = [{"path": f.path, "rule": f.rule, "snippet": f.snippet}
               for f in sorted(findings, key=lambda f: f.key())]
    path.write_text(json.dumps({"version": 1, "entries": entries},
                               indent=1) + "\n", encoding="utf-8")
    return path


def apply_baseline(findings: Sequence[Finding],
                   baseline: Counter) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, baselined). Baseline entries are a
    multiset so N identical lines need N entries."""
    budget = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            f.baselined = True
            old.append(f)
        else:
            new.append(f)
    return new, old


def findings_to_json(new: Sequence[Finding],
                     baselined: Sequence[Finding]) -> str:
    return json.dumps({
        "findings": [asdict(f) for f in new],
        "baselined": [asdict(f) for f in baselined],
        "counts": {"new": len(new), "baselined": len(baselined)},
    }, indent=1)
