"""dslint phase 2: interprocedural rules (DS011–DS014).

These consume the package-wide :class:`~tools.dslint.symbols.SymbolTable`
built in phase 1 — they see *across* modules, which the per-file rules
(DS001–DS010) deliberately don't:

DS011  donated buffer read after dispatch through a jit entry defined in
       ANOTHER module (or through one level of helper inlining) — the
       cross-module complement of DS003
DS012  fault-site integrity: every fired site literal is declared
       (KNOWN_SITES / register_site), every declared site is actually
       fired somewhere, every site is documented in docs/ROBUSTNESS.md,
       and public inference entries that dispatch a donated jit fire
       their site before the dispatch
DS013  env-flag registry: literal ``DS_*`` reads under ``deepspeed_tpu/``
       must route through ``utils/env.py::resolve_flag`` against a
       declared flag, and every declared bool flag defaults off (the
       off-state is the bit-reference)
DS014  telemetry schema drift: code-registered metric/trace names, the
       checked-in ``tools/dslint/telemetry_schema.json``, and
       docs/OBSERVABILITY.md must agree in both directions

Each rule implements ``check_package(table, docs_root=..., partial=...)``.
``partial=True`` (the ``--closure`` quick mode, where only a changed-file
closure was parsed) disables the completeness directions — "declared but
never fired", "in schema but not in code" — that are only meaningful
over the whole tree.
"""

import ast
import fnmatch
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.dslint.core import REPO_ROOT, Finding
from tools.dslint.rules import (FUNC_TYPES, DonationHazard, _parents,
                                _stmt_of, _store_names)
from tools.dslint.symbols import (JitEntry, SymbolTable, _callee_key,
                                  _dotted)

DEFAULT_SCHEMA = Path(__file__).resolve().parent / "telemetry_schema.json"


class InterprocRule:
    id = "DS0XX"
    name = "base"
    autofixable = False
    rationale = ""

    def check_package(self, table: SymbolTable,
                      docs_root: Optional[Path] = None,
                      schema_path: Optional[Path] = None,
                      partial: bool = False) -> List[Finding]:
        raise NotImplementedError

    def _f(self, path: str, line: int, message: str,
           col: int = 0) -> Finding:
        return Finding(self.id, path, line, col, message)


# --------------------------------------------------------------------------
class DonationFlowHazard(InterprocRule):
    id = "DS011"
    name = "donated-buffer-use-after-dispatch"
    autofixable = False
    rationale = ("DS003 only sees jit registrations in the same file; a "
                 "buffer donated through an entry point defined in another "
                 "module — or passed through a helper that forwards it into "
                 "a donated position — is just as dead after the call, and "
                 "reading it returns garbage on TPU")

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        by_key: Dict[Tuple[str, str], List[JitEntry]] = {}
        for e in table.jit_entries:
            by_key.setdefault(e.key, []).append(e)
        if not by_key:
            return []
        out: List[Finding] = []
        ds003 = DonationHazard()
        for path, tree, lines in table.files:
            local = set(ds003._collect_donating(tree))
            for call in ast.walk(tree):
                if not isinstance(call, ast.Call):
                    continue
                key = _callee_key(call.func)
                if key is None or key in local \
                        or key not in by_key:
                    continue              # same-file entries are DS003's
                fn = None
                for p in _parents(call):
                    if isinstance(p, FUNC_TYPES):
                        fn = p
                        break
                if fn is None:
                    continue
                for entry in by_key[key]:
                    if entry.key[0] == "name" and entry.path != path:
                        continue          # bare names bind module-locally
                    for pos in entry.donate:
                        if pos < len(call.args) and isinstance(
                                call.args[pos], ast.Name):
                            out.extend(self._use_after(
                                fn, call, call.args[pos].id,
                                entry, path))
        return _dedupe(out)

    def _use_after(self, fn, call, name: str, entry: JitEntry,
                   path: str) -> List[Finding]:
        stmt = _stmt_of(call)
        if isinstance(stmt, ast.Assign) and any(
                name in _store_names(t) for t in stmt.targets):
            return []
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) and \
                name in _store_names(stmt.target):
            return []
        call_pos = (call.end_lineno or call.lineno,
                    call.end_col_offset or call.col_offset)
        events = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and n.id == name:
                if any(p is call for p in _parents(n)) or n is call:
                    continue
                events.append(((n.lineno, n.col_offset),
                               isinstance(n.ctx, ast.Store), n))
        events.sort(key=lambda e: e[0])
        via = (" (donates through a helper)" if entry.helper_of
               else f" (jit entry at {entry.path}:{entry.line})")
        for pos, is_store, n in events:
            if pos <= call_pos:
                continue
            if is_store:
                return []
            return [self._f(
                path, n.lineno,
                f"`{name}` was donated to `{entry.key[1]}`{via} but is "
                f"read afterwards — the buffer may have been aliased into "
                f"the output; rebind or copy before donating",
                col=n.col_offset)]
        return []


# --------------------------------------------------------------------------
class FaultSiteIntegrity(InterprocRule):
    id = "DS012"
    name = "fault-site-integrity"
    autofixable = False
    rationale = ("the chaos harness can only exercise sites that exist: a "
                 "fired literal nobody declared is untestable, a declared "
                 "site nobody fires is dead coverage, an undocumented site "
                 "is invisible to operators, and a public entry that "
                 "dispatches a donated jit without firing its site first "
                 "can't be fault-injected at the moment that matters")

    _ENTRY_PATHS = re.compile(r"(^|/)inference/")

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        out: List[Finding] = []
        declared = set(table.known_sites) | set(table.registered_sites)
        fired = {fs.site for fs in table.fire_sites}

        # (1) fired literal nobody declared — production code only; tests
        # fire synthetic sites at FaultInjector directly on purpose
        if declared:
            for fs in table.fire_sites:
                if fs.path.startswith("deepspeed_tpu/") \
                        and fs.site not in declared:
                    out.append(self._f(
                        fs.path, fs.line,
                        f"fault site '{fs.site}' is fired but not declared "
                        f"in KNOWN_SITES (or via register_site) — the chaos "
                        f"harness can't target it"))

        if not partial:
            # (2) declared site nobody fires
            for site in sorted(table.known_sites - fired):
                path, line = table.known_sites_loc or ("", 0)
                out.append(self._f(
                    path, line,
                    f"fault site '{site}' is declared in KNOWN_SITES but "
                    f"never fired anywhere — stale registration (remove it "
                    f"or wire the fire)"))
            for site, (path, line) in sorted(table.registered_sites.items()):
                if site not in fired:
                    out.append(self._f(
                        path, line,
                        f"fault site '{site}' is registered via "
                        f"register_site but never fired — stale "
                        f"registration"))
            # (3) declared site missing from the robustness doc
            out.extend(self._check_docs(table, declared, docs_root))

        # (4) public inference entries must fire before donated dispatch
        out.extend(self._check_fire_before_dispatch(table))
        return _dedupe(out)

    def _check_docs(self, table, declared: Set[str],
                    docs_root: Optional[Path]) -> List[Finding]:
        root = Path(docs_root) if docs_root is not None else REPO_ROOT / "docs"
        doc = root / "ROBUSTNESS.md"
        if not doc.exists() or not declared:
            return []
        text = doc.read_text(encoding="utf-8")
        out = []
        for site in sorted(declared):
            if site not in text:
                path, line = (table.known_sites_loc
                              or next(iter(table.registered_sites.values()),
                                      ("", 0)))
                if site in table.registered_sites:
                    path, line = table.registered_sites[site]
                out.append(self._f(
                    path, line,
                    f"fault site '{site}' is not documented in "
                    f"docs/ROBUSTNESS.md — add it to the site table"))
        return out

    def _check_fire_before_dispatch(self, table) -> List[Finding]:
        by_key: Dict[Tuple[str, str], List[JitEntry]] = {}
        for e in table.jit_entries:
            by_key.setdefault(e.key, []).append(e)
        if not by_key:
            return []
        # functions known to fire (directly or by forwarding)
        firing_fns: Set[Tuple[str, str]] = {
            (fs.path, fs.fn) for fs in table.fire_sites if fs.fn}
        firing_fns |= set(table.fire_forwarders)
        fires_by_fn: Dict[Tuple[str, str], List[int]] = {}
        for fs in table.fire_sites:
            if fs.fn:
                fires_by_fn.setdefault((fs.path, fs.fn), []).append(fs.line)
        forwarder_names = {fn for (_, fn) in table.fire_forwarders}
        out: List[Finding] = []
        for path, tree, lines in table.files:
            if not self._ENTRY_PATHS.search(path):
                continue
            for fn in ast.walk(tree):
                if not isinstance(fn, FUNC_TYPES) \
                        or fn.name.startswith("_"):
                    continue
                fire_lines = list(fires_by_fn.get((path, fn.name), []))
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    key = _callee_key(call.func)
                    if key is not None and key[1] in forwarder_names:
                        fire_lines.append(call.lineno)
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    key = _callee_key(call.func)
                    if key is None:
                        continue
                    entries = [e for e in by_key.get(key, ())
                               if e.key[0] == "attr" or e.path == path]
                    if not entries:
                        continue
                    entry = entries[0]
                    if entry.helper_of and (entry.path, entry.key[1]) \
                            in firing_fns:
                        continue      # the helper fires its own site
                    if any(fl <= call.lineno for fl in fire_lines):
                        continue
                    out.append(self._f(
                        path, call.lineno,
                        f"public entry `{fn.name}` dispatches donated jit "
                        f"`{key[1]}` without firing its fault site first — "
                        f"chaos tests can't inject at this dispatch; call "
                        f"maybe_fire(<site>) (or a fire-forwarding helper) "
                        f"before the dispatch"))
                    break             # one finding per public entry
        return out


# --------------------------------------------------------------------------
class EnvFlagRegistry(InterprocRule):
    id = "DS013"
    name = "env-flag-registry"
    autofixable = False
    rationale = ("every DS_* knob must be declared once in utils/env.py "
                 "FLAGS (name, type, default) and read via resolve_flag() "
                 "— scattered os.environ reads drift in parsing and "
                 "default, and a bool flag that defaults ON has no "
                 "bit-reference off-state")

    _EXEMPT = re.compile(r"(^|/)(tools|tests)/|conftest|(^|/)launcher/")

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        out: List[Finding] = []
        flags_path = table.flags_path

        for r in table.env_reads:
            if not r.var.startswith("DS_"):
                continue
            if r.how == "resolve_flag":
                if flags_path is not None \
                        and r.var not in table.flags_declared:
                    out.append(self._f(
                        r.path, r.line,
                        f"resolve_flag('{r.var}') reads an undeclared "
                        f"flag — add it to utils/env.py FLAGS with a "
                        f"typed default"))
                continue
            # raw read (os.environ / os.getenv / mapping.get)
            if not r.path.startswith("deepspeed_tpu/"):
                continue
            if r.path == flags_path or self._EXEMPT.search(r.path):
                continue
            out.append(self._f(
                r.path, r.line,
                f"direct env read of '{r.var}' bypasses the FLAGS "
                f"registry — declare it in utils/env.py and read it via "
                f"resolve_flag('{r.var}')"))

        if not partial:
            for name, (kind, default, path, line) in sorted(
                    table.flags_declared.items()):
                if kind == "bool" and default is True:
                    out.append(self._f(
                        path, line,
                        f"bool flag {name} defaults ON — the unset "
                        f"environment must be the bit-exact reference "
                        f"path; default it off and opt in explicitly"))
        return _dedupe(out)


# --------------------------------------------------------------------------
class TelemetrySchemaDrift(InterprocRule):
    id = "DS014"
    name = "telemetry-schema-drift"
    autofixable = False
    rationale = ("dashboards and alerts key on metric/trace names; a name "
                 "registered in code but absent from the schema (or "
                 "docs/OBSERVABILITY.md) is invisible to operators, and a "
                 "schema entry no code registers is a dead panel — the "
                 "checked-in telemetry_schema.json is the contract both "
                 "sides are held to")

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        spath = Path(schema_path) if schema_path is not None \
            else DEFAULT_SCHEMA
        if not spath.exists():
            return []        # no contract to enforce (fixture trees)
        try:
            schema = json.loads(spath.read_text(encoding="utf-8"))
        except (OSError, ValueError) as e:
            return [self._f(_rel(spath), 1,
                            f"unreadable telemetry schema: {e}")]
        metrics = set(schema.get("metrics", ()))
        events = set(schema.get("events", ()))
        patterns = list(schema.get("metric_patterns", ()))
        known = metrics | events
        out: List[Finding] = []

        code_names: Set[str] = set()
        code_patterns: Set[str] = set()
        for reg in table.metric_regs:
            if self._TEST_PATHS.search(reg.path):
                continue      # unit tests register throwaway names
            target = events if reg.kind == "event" else metrics
            if reg.pattern:
                code_patterns.add(reg.name)
                if reg.name not in patterns:
                    out.append(self._f(
                        reg.path, reg.line,
                        f"dynamic telemetry name pattern '{reg.name}' is "
                        f"not in telemetry_schema.json metric_patterns — "
                        f"declare the family"))
                continue
            code_names.add(reg.name)
            if reg.name not in target \
                    and not _matches_any(reg.name, patterns):
                out.append(self._f(
                    reg.path, reg.line,
                    f"telemetry name '{reg.name}' ({reg.kind}) is "
                    f"registered in code but missing from "
                    f"telemetry_schema.json — add it (and a row in "
                    f"docs/OBSERVABILITY.md)"))

        if not partial:
            for name in sorted(known - code_names):
                out.append(self._f(
                    _rel(spath), 1,
                    f"schema entry '{name}' is registered by no code "
                    f"path — stale; remove it from telemetry_schema.json "
                    f"and docs/OBSERVABILITY.md"))
            for pat in patterns:
                if pat not in code_patterns:
                    out.append(self._f(
                        _rel(spath), 1,
                        f"schema pattern '{pat}' matches no dynamic "
                        f"registration in code — stale"))
            out.extend(self._check_docs(known, patterns, docs_root))
        return _dedupe(out)

    # .. docs/OBSERVABILITY.md two-way check ............................

    _TOKEN = re.compile(r"`([a-z0-9_{}|,<>*]+)`")
    _TEST_PATHS = re.compile(r"(^|/)tests/")

    def _check_docs(self, known: Set[str], patterns: Sequence[str],
                    docs_root: Optional[Path]) -> List[Finding]:
        root = Path(docs_root) if docs_root is not None else REPO_ROOT / "docs"
        doc = root / "OBSERVABILITY.md"
        if not doc.exists():
            return []
        text = doc.read_text(encoding="utf-8")
        out: List[Finding] = []
        rel = _rel(doc)
        # every backticked token in the doc, with {a|b}/{a,b} brace
        # notation expanded — so `serving_step_{admission,decode}_s`
        # documents both concrete names
        doc_names: Set[str] = set()
        for tok in self._TOKEN.findall(text):
            doc_names.update(_expand_doc_token(tok))
        # schema -> docs: every contract name appears somewhere in the doc
        for name in sorted(known):
            if name not in text and name not in doc_names \
                    and not any(fnmatch.fnmatch(name, d)
                                for d in doc_names if "*" in d):
                out.append(self._f(
                    rel, 1,
                    f"telemetry name '{name}' is in the schema but not "
                    f"mentioned in docs/OBSERVABILITY.md — document it"))
        # docs -> schema: metric-looking tokens in table first cells must
        # be real contract names (catches doc rows for renamed metrics)
        for i, ln in enumerate(text.splitlines(), 1):
            s = ln.strip()
            if not s.startswith("|"):
                continue
            first = s.split("|")[1] if s.count("|") >= 2 else ""
            for tok in self._TOKEN.findall(first):
                for cand in _expand_doc_token(tok):
                    if "_" not in cand:
                        continue      # prose words, not telemetry names
                    if cand in known or _matches_any(cand, patterns) \
                            or any(fnmatch.fnmatch(k, cand)
                                   for k in known):
                        continue
                    out.append(self._f(
                        rel, i,
                        f"docs/OBSERVABILITY.md names '{cand}' which is "
                        f"not in telemetry_schema.json — stale doc row "
                        f"or missing schema entry"))
        return out


def _expand_doc_token(tok: str) -> List[str]:
    """``serving_{ttft|tbt}_s`` → both concrete names; ``<x>``-style
    placeholders become ``*`` globs."""
    tok = re.sub(r"<[^>]*>", "*", tok)
    m = re.search(r"\{([^}]*)\}", tok)
    if not m:
        return [tok]
    out: List[str] = []
    for alt in re.split(r"[|,]", m.group(1)):
        out.extend(_expand_doc_token(
            tok[:m.start()] + alt.strip() + tok[m.end():]))
    return out


def _matches_any(name: str, patterns: Sequence[str]) -> bool:
    return any(fnmatch.fnmatch(name, p) for p in patterns)


def _rel(p: Path) -> str:
    try:
        return p.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen: Set[Tuple[str, str, int, str]] = set()
    out = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# --------------------------------------------------------------------------

def interproc_rules() -> List[InterprocRule]:
    # dataflow (v3) imports InterprocRule from this module, so its
    # import must stay inside the function body
    from tools.dslint.dataflow import dataflow_rules
    return [DonationFlowHazard(), FaultSiteIntegrity(),
            EnvFlagRegistry(), TelemetrySchemaDrift()] + dataflow_rules()


def interproc_catalog() -> List[Dict[str, str]]:
    return [{"id": r.id, "name": r.name,
             "autofixable": r.autofixable, "rationale": r.rationale}
            for r in interproc_rules()]
