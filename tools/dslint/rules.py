"""dslint rules: the JAX/TPU-specific checks (DS001–DS010).

Each rule encodes an invariant the runtime actually depends on (see
docs/LINT.md for rationale and before/after examples):

DS001  blocking host sync inside a hot loop (float()/bool()/.item()/
       np.asarray()/jax.device_get() per iteration of a step/decode loop)
DS002  jit cache fragmentation (jit in a loop, jit(lambda), jitting a
       fresh nested def per call, unhashable static-arg defaults)
DS003  donated buffer read after the jitted call that consumed it
DS004  Python if/while branching on a traced value inside a jitted fn
DS005  os.environ read outside the config/constants layer or at import
DS006  bare except / except Exception that silently passes
DS007  mutable default argument
DS008  jnp./device work executed at module import scope
DS009  pointer/marker file in a checkpoint path replaced with a plain
       in-place write instead of tmp + fsync + os.replace
DS010  unseeded randomness in the inference layer (process-global
       np.random draws, jax PRNGKeys derived from time/os entropy)

All heuristics are deliberately lexical (pure ``ast``): they can't see
through aliases or cross-module calls, so each rule favors precision on
the failure modes this repo has actually shipped (PR 2's
_flush_monitor_buffer host-sync bug, the two-compiled-programs serving
contract) over recall. Suppress intentional hits inline with a reason.
"""

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.dslint.core import Finding

# functions whose loops count as hot paths for DS001: the step/decode/
# update loops where one stray sync serializes the device pipeline
HOT_NAME = re.compile(r"(^|_)(step|train|decode|generate|update|micro)",
                      re.IGNORECASE)

LOOP_TYPES = (ast.For, ast.AsyncFor, ast.While,
              ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _parents(node: ast.AST) -> Iterator[ast.AST]:
    p = getattr(node, "_ds_parent", None)
    while p is not None:
        yield p
        p = getattr(p, "_ds_parent", None)


def _enclosing(node: ast.AST, types) -> Optional[ast.AST]:
    for p in _parents(node):
        if isinstance(p, types):
            return p
    return None


def _loop_between(node: ast.AST, fn: ast.AST) -> bool:
    """True when a loop encloses ``node`` without leaving ``fn``.

    A comprehension's *first* iterable is evaluated exactly once, so a
    node sitting inside ``generators[0].iter`` is not per-iteration work
    and the comprehension does not count as its enclosing loop.
    """
    for p in _parents(node):
        if p is fn:
            return False
        if isinstance(p, LOOP_TYPES):
            gens = getattr(p, "generators", None)
            if gens and _contains(gens[0].iter, node):
                continue
            return True
    return False


def _contains(root: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(root))


def _dotted(func: ast.AST) -> List[str]:
    """['jax', 'random', 'split'] for jax.random.split; [] if not a
    plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return list(reversed(parts))
    return []


def _stmt_of(node: ast.AST) -> Optional[ast.stmt]:
    if isinstance(node, ast.stmt):
        return node
    for p in _parents(node):
        if isinstance(p, ast.stmt):
            return p
    return None


def _store_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
    return out


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set,
                         ast.ListComp, ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = _dotted(node.func)
        return chain[-1:] in (["list"], ["dict"], ["set"], ["bytearray"]) \
            and len(chain) == 1
    return False


class Rule:
    id = "DS000"
    name = "base"
    autofixable = False
    rationale = ""

    def check(self, tree: ast.AST, lines: Sequence[str],
              path: str) -> List[Finding]:
        raise NotImplementedError

    def _f(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


# --------------------------------------------------------------------------
class BlockingHostSync(Rule):
    id = "DS001"
    name = "blocking-host-sync"
    autofixable = False
    rationale = ("float()/bool()/.item()/np.asarray()/jax.device_get() per "
                 "iteration of a step/decode loop blocks on the device and "
                 "serializes the pipeline; accumulate on device and pull "
                 "once (batched jax.device_get) after the loop")

    # convergence tests pull the loss scalar every step on purpose —
    # that's the assertion, not a pipeline bug
    _TEST_PATHS = re.compile(r"(^|/)tests/")

    def check(self, tree, lines, path):
        if self._TEST_PATHS.search(path.replace("\\", "/")):
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._sync_kind(node)
            if what is None:
                continue
            fn = _enclosing(node, FUNC_TYPES)
            if fn is None or not HOT_NAME.search(fn.name):
                continue
            if not _loop_between(node, fn):
                continue
            out.append(self._f(
                path, node,
                f"blocking host sync `{what}` inside a loop of hot "
                f"function `{fn.name}` — accumulate on device and do one "
                f"batched pull (jax.device_get) after the loop"))
        return out

    @staticmethod
    def _sync_kind(call: ast.Call) -> Optional[str]:
        chain = _dotted(call.func)
        if chain in (["float"], ["bool"]):
            if not call.args or isinstance(call.args[0], ast.Constant):
                return None
            return f"{chain[0]}(...)"
        if (isinstance(call.func, ast.Attribute) and call.func.attr == "item"
                and not call.args):
            return ".item()"
        if chain[:1] in (["np"], ["numpy"]) and chain[-1:] in (
                ["asarray"], ["array"]):
            return f"{'.'.join(chain)}(...)"
        if chain == ["jax", "device_get"]:
            return "jax.device_get(...)"
        return None


# --------------------------------------------------------------------------
class JitCacheFragmentation(Rule):
    id = "DS002"
    name = "jit-cache-fragmentation"
    autofixable = False
    rationale = ("jax.jit keyed on a fresh callable (loop-local jit, "
                 "jit(lambda), re-jitted nested def) or an unhashable "
                 "static default never hits the compile cache — every call "
                 "recompiles")

    def check(self, tree, lines, path):
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self._is_jit(node.func):
                fn = _enclosing(node, FUNC_TYPES)
                if fn is not None and _loop_between(node, fn):
                    out.append(self._f(
                        path, node,
                        "jax.jit called inside a loop — every iteration "
                        "wraps a fresh callable and recompiles; hoist the "
                        "jit out of the loop"))
                if any(isinstance(a, ast.Lambda) for a in node.args):
                    out.append(self._f(
                        path, node,
                        "lambda passed to jax.jit — a new lambda object per "
                        "evaluation defeats the jit cache; use a module-"
                        "level def"))
            if isinstance(node, FUNC_TYPES):
                out.extend(self._check_def(node, path))
        return out

    @staticmethod
    def _is_jit(func: ast.AST) -> bool:
        chain = _dotted(func)
        return chain == ["jax", "jit"] or chain == ["jit"]

    def _jit_decorator(self, dec: ast.AST) -> Optional[ast.AST]:
        """The decorator node when it applies jax.jit (plain or via
        functools.partial), else None."""
        if self._is_jit(dec):
            return dec
        if isinstance(dec, ast.Call):
            chain = _dotted(dec.func)
            if chain[-1:] == ["jit"] and chain[:-1] in ([], ["jax"]):
                return dec
            if chain[-1:] == ["partial"] and dec.args \
                    and self._is_jit(dec.args[0]):
                return dec
        return None

    def _check_def(self, node, path) -> List[Finding]:
        out = []
        jit_dec = None
        for dec in node.decorator_list:
            jit_dec = self._jit_decorator(dec)
            if jit_dec is not None:
                break
        if jit_dec is None:
            return out
        enclosing_fn = _enclosing(node, FUNC_TYPES)
        if enclosing_fn is not None and not self._escapes(node.name,
                                                          enclosing_fn):
            out.append(self._f(
                path, node,
                f"`{node.name}` is re-defined and re-jitted on every call "
                f"of `{enclosing_fn.name}` — each definition is a new "
                f"cache key; hoist it or cache the jitted function"))
        out.extend(self._check_static_defaults(node, jit_dec, path))
        return out

    @staticmethod
    def _escapes(name: str, enclosing_fn: ast.AST) -> bool:
        """A nested jitted def that is cached (stored on self/a dict) or
        returned survives the enclosing call — not a per-call recompile.
        Only the function OBJECT escaping counts: ``return inner(x)``
        calls it and discards it, which is exactly the per-call pattern
        the rule exists to catch."""
        def _obj_escapes(value: ast.AST) -> bool:
            for sub in ast.walk(value):
                if not (isinstance(sub, ast.Name) and sub.id == name):
                    continue
                parent = getattr(sub, "_ds_parent", None)
                if isinstance(parent, ast.Call) and parent.func is sub:
                    continue
                return True
            return False

        for n in ast.walk(enclosing_fn):
            if isinstance(n, ast.Return) and n.value is not None \
                    and _obj_escapes(n.value):
                return True
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in n.targets) and _obj_escapes(n.value):
                return True
        return False

    def _check_static_defaults(self, node, jit_dec, path) -> List[Finding]:
        out = []
        statics_nums: List[int] = []
        statics_names: List[str] = []
        if isinstance(jit_dec, ast.Call):
            for kw in jit_dec.keywords:
                val = kw.value
                items = val.elts if isinstance(
                    val, (ast.Tuple, ast.List)) else [val]
                if kw.arg == "static_argnums":
                    statics_nums = [i.value for i in items
                                    if isinstance(i, ast.Constant)
                                    and isinstance(i.value, int)]
                elif kw.arg == "static_argnames":
                    statics_names = [i.value for i in items
                                     if isinstance(i, ast.Constant)
                                     and isinstance(i.value, str)]
        args = list(node.args.posonlyargs) + list(node.args.args)
        defaults = list(node.args.defaults)
        # defaults align with the TAIL of the positional args
        offset = len(args) - len(defaults)
        for i, d in enumerate(defaults):
            ai = offset + i
            is_static = ai in statics_nums or args[ai].arg in statics_names
            if is_static and _is_mutable_literal(d):
                out.append(self._f(
                    path, d,
                    f"static arg `{args[ai].arg}` of jitted `{node.name}` "
                    f"defaults to an unhashable value — jit's cache lookup "
                    f"raises (or hashes by identity) on it; use a tuple or "
                    f"frozen value"))
        for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults):
            if d is not None and a.arg in statics_names \
                    and _is_mutable_literal(d):
                out.append(self._f(
                    path, d,
                    f"static kwarg `{a.arg}` of jitted `{node.name}` "
                    f"defaults to an unhashable value"))
        return out


# --------------------------------------------------------------------------
class DonationHazard(Rule):
    id = "DS003"
    name = "donated-buffer-reuse"
    autofixable = False
    rationale = ("an argument listed in donate_argnums is dead after the "
                 "jitted call — XLA may have aliased its buffer into the "
                 "output; reading it is undefined (garbage on TPU, silent "
                 "correctness bug)")

    def check(self, tree, lines, path):
        registry = self._collect_donating(tree)
        if not registry:
            return []
        out = []
        for fn in ast.walk(tree):
            if not isinstance(fn, FUNC_TYPES):
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                key = self._callee_key(call.func)
                if key is None or key not in registry:
                    continue
                for pos in registry[key]:
                    if pos < len(call.args) and isinstance(
                            call.args[pos], ast.Name):
                        out.extend(self._check_use_after(
                            fn, call, call.args[pos].id, key[1], path))
        return out

    # -- registry: name/attr -> donated positions -------------------------
    def _collect_donating(self, tree) -> Dict[Tuple[str, str], List[int]]:
        reg: Dict[Tuple[str, str], List[int]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call):
                continue
            call = node.value
            if _dotted(call.func) not in (["jax", "jit"], ["jit"]):
                continue
            donated: List[int] = []
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    items = kw.value.elts if isinstance(
                        kw.value, (ast.Tuple, ast.List)) else [kw.value]
                    donated = [i.value for i in items
                               if isinstance(i, ast.Constant)
                               and isinstance(i.value, int)]
            if not donated:
                continue
            # jitting a bound method (jax.jit(self._fn)) drops `self` from
            # the arg positions, so recorded positions apply as-is to the
            # call sites; both Name and self.attr targets are tracked
            for t in node.targets:
                key = self._callee_key(t)
                if key is not None:
                    reg[key] = donated
        return reg

    @staticmethod
    def _callee_key(node: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(node, ast.Name):
            return ("name", node.id)
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id in ("self", "cls"):
            return ("attr", node.attr)
        return None

    # -- use-after-donation scan ------------------------------------------
    def _check_use_after(self, fn, call, name, callee, path) -> List[Finding]:
        stmt = _stmt_of(call)
        # the consuming statement's own assignment rebinds the name: safe
        if isinstance(stmt, ast.Assign) and any(
                name in _store_names(t) for t in stmt.targets):
            return []
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) and \
                name in _store_names(stmt.target):
            return []
        call_pos = (call.end_lineno or call.lineno,
                    call.end_col_offset or call.col_offset)
        events = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and n.id == name:
                if any(p is call for p in _parents(n)) or n is call:
                    continue
                events.append(((n.lineno, n.col_offset),
                               isinstance(n.ctx, ast.Store), n))
        events.sort(key=lambda e: e[0])
        for pos, is_store, n in events:
            if pos <= call_pos:
                continue
            if is_store:
                return []        # rebound before any later read
            return [self._f(
                path, n,
                f"`{name}` was donated to `{callee}` (donate_argnums) but "
                f"is read afterwards — the buffer may have been aliased "
                f"into the output; rebind or copy before donating")]
        return []


# --------------------------------------------------------------------------
class TracedPythonBranch(Rule):
    id = "DS004"
    name = "traced-python-branch"
    autofixable = False
    rationale = ("Python if/while on a traced value inside a jitted "
                 "function raises TracerBoolConversionError at best and "
                 "silently bakes one branch into the compiled program at "
                 "worst; use lax.cond/jnp.where or mark the arg static")

    _OK_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
    _OK_CALLS = {"len", "isinstance", "hasattr", "getattr", "callable"}

    def check(self, tree, lines, path):
        jitted = self._jitted_defs(tree)
        out = []
        for fn, statics in jitted:
            params = [a.arg for a in (list(fn.args.posonlyargs)
                                      + list(fn.args.args)
                                      + list(fn.args.kwonlyargs))]
            traced = {p for p in params if p not in statics
                      and p not in ("self", "cls")}
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                bad = self._traced_name_in_test(node.test, traced)
                if bad:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(self._f(
                        path, node,
                        f"Python `{kind}` on traced argument `{bad}` inside "
                        f"jitted `{fn.name}` — branch with jnp.where/"
                        f"lax.cond, or make `{bad}` a static_argnum"))
        return out

    # -- which defs are jitted, and which of their params are static ------
    def _jitted_defs(self, tree):
        frag = JitCacheFragmentation()
        # name -> (static positions, static names, bound-method offset)
        marked: Dict[str, Tuple[List[int], List[str], int]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and frag._is_jit(node.func) \
                    and node.args:
                target = node.args[0]
                nums, names = self._statics_of(node)
                if isinstance(target, ast.Name):
                    marked[target.id] = (nums, names, 0)
                elif isinstance(target, ast.Attribute) and isinstance(
                        target.value, ast.Name) and target.value.id == "self":
                    # bound method: call-site positions skip `self`
                    marked[target.attr] = (nums, names, 1)
        out = []
        for fn in ast.walk(tree):
            if not isinstance(fn, FUNC_TYPES):
                continue
            dec = None
            for d in fn.decorator_list:
                dec = frag._jit_decorator(d)
                if dec is not None:
                    break
            if dec is not None:
                nums, names = (self._statics_of(dec)
                               if isinstance(dec, ast.Call) else ([], []))
                out.append((fn, self._static_params(fn, nums, names, 0)))
            elif fn.name in marked:
                nums, names, off = marked[fn.name]
                out.append((fn, self._static_params(fn, nums, names, off)))
        return out

    @staticmethod
    def _statics_of(call: ast.Call) -> Tuple[List[int], List[str]]:
        nums: List[int] = []
        names: List[str] = []
        for kw in call.keywords:
            items = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            if kw.arg == "static_argnums":
                nums = [i.value for i in items
                        if isinstance(i, ast.Constant)
                        and isinstance(i.value, int)]
            elif kw.arg == "static_argnames":
                names = [i.value for i in items
                         if isinstance(i, ast.Constant)
                         and isinstance(i.value, str)]
        return nums, names

    @staticmethod
    def _static_params(fn, nums, names, offset) -> Set[str]:
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        statics = set(names)
        for p in nums:
            idx = p + offset
            if 0 <= idx < len(args):
                statics.add(args[idx].arg)
        return statics

    def _traced_name_in_test(self, test: ast.AST,
                             traced: Set[str]) -> Optional[str]:
        for n in ast.walk(test):
            if not (isinstance(n, ast.Name) and n.id in traced
                    and isinstance(n.ctx, ast.Load)):
                continue
            # climb through subscripts so `x['a'].shape` reads like
            # `x.shape` — indexing changes the leaf, not the question
            cur: ast.AST = n
            parent = getattr(cur, "_ds_parent", None)
            while isinstance(parent, ast.Subscript) and parent.value is cur:
                cur = parent
                parent = getattr(cur, "_ds_parent", None)
            if isinstance(parent, ast.Attribute) \
                    and parent.attr in self._OK_ATTRS:
                continue
            if isinstance(parent, ast.Call) and \
                    _dotted(parent.func)[-1:] != [] and \
                    _dotted(parent.func)[-1] in self._OK_CALLS:
                continue
            if isinstance(parent, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                    for op in parent.ops):
                # `x is None` and `"key" in x` test pytree STRUCTURE,
                # which is static under trace
                continue
            return n.id
        return None


# --------------------------------------------------------------------------
class EnvReadOutsideConfig(Rule):
    id = "DS005"
    name = "env-read-outside-config"
    autofixable = False
    rationale = ("os.environ scattered through library code makes behavior "
                 "depend on ambient state that tests and serving replicas "
                 "don't pin; route env through the config/constants layer. "
                 "Module-scope reads additionally freeze the value at "
                 "import order")

    # the sanctioned env layer: config/constants modules, environment
    # reporting, process bootstrap (launcher), test harness, entry scripts
    _ALLOWED = re.compile(
        r"(config|constants|env_report|conftest)"
        r"|(^|/)launcher/"
        r"|(^|/)tools/")

    def check(self, tree, lines, path):
        allowed_file = bool(self._ALLOWED.search(path.replace("\\", "/")))
        out = []
        for node in ast.walk(tree):
            kind = self._env_read(node)
            if kind is None:
                continue
            fn = _enclosing(node, FUNC_TYPES)
            if fn is None:
                out.append(self._f(
                    path, node,
                    f"`{kind}` at module import scope freezes the value at "
                    f"import time — read it inside the function that needs "
                    f"it (or in the config layer)"))
            elif not allowed_file:
                out.append(self._f(
                    path, node,
                    f"`{kind}` outside the config/constants layer — thread "
                    f"the setting through config so replicas and tests can "
                    f"pin it"))
        return out

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os")

    def _env_read(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Subscript) and self._is_environ(node.value):
            return "os.environ[...]"
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if chain == ["os", "getenv"]:
                return "os.getenv(...)"
            if isinstance(node.func, ast.Attribute) and self._is_environ(
                    node.func.value):
                return f"os.environ.{node.func.attr}(...)"
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            if any(self._is_environ(c) for c in node.comparators):
                return "... in os.environ"
        return None


# --------------------------------------------------------------------------
class OverbroadExcept(Rule):
    id = "DS006"
    name = "overbroad-except"
    autofixable = False
    rationale = ("a bare except (or `except Exception: pass`) swallows "
                 "KeyboardInterrupt/compile errors/real bugs silently — "
                 "the failure surfaces later as wrong numerics or a hang; "
                 "catch the specific exception or at least log it")

    _BROAD = {"Exception", "BaseException"}

    def check(self, tree, lines, path):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(self._f(
                    path, node,
                    "bare `except:` also catches KeyboardInterrupt/"
                    "SystemExit — name the exception type"))
                continue
            names = self._type_names(node.type)
            swallows = all(isinstance(s, (ast.Pass, ast.Continue))
                           for s in node.body)
            if names & self._BROAD and swallows:
                out.append(self._f(
                    path, node,
                    f"`except {'/'.join(sorted(names & self._BROAD))}` that "
                    f"silently passes — narrow the type or log the failure"))
        return out

    @staticmethod
    def _type_names(t: ast.AST) -> Set[str]:
        names: Set[str] = set()
        nodes = t.elts if isinstance(t, ast.Tuple) else [t]
        for n in nodes:
            chain = _dotted(n)
            if chain:
                names.add(chain[-1])
        return names


# --------------------------------------------------------------------------
class MutableDefaultArg(Rule):
    id = "DS007"
    name = "mutable-default-arg"
    autofixable = True
    rationale = ("a mutable default is created once at def time and shared "
                 "across every call — state leaks between calls; default "
                 "to None and construct inside")

    def check(self, tree, lines, path):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, FUNC_TYPES):
                continue
            args = list(node.args.posonlyargs) + list(node.args.args)
            offset = len(args) - len(node.args.defaults)
            for i, d in enumerate(node.args.defaults):
                if _is_mutable_literal(d):
                    out.append(self._f(
                        path, d,
                        f"mutable default for `{args[offset + i].arg}` in "
                        f"`{node.name}` is shared across calls — use None "
                        f"and construct inside"))
            for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults):
                if d is not None and _is_mutable_literal(d):
                    out.append(self._f(
                        path, d,
                        f"mutable default for `{a.arg}` in `{node.name}` "
                        f"is shared across calls — use None and construct "
                        f"inside"))
        return out


# --------------------------------------------------------------------------
class ImportScopeDeviceWork(Rule):
    id = "DS008"
    name = "import-scope-device-work"
    autofixable = False
    rationale = ("jnp./device calls at module scope run at import: they "
                 "pick a backend before the app configures one, allocate "
                 "HBM in every process that merely imports the module, and "
                 "serialize startup behind compiles")

    # jax.* sub-apis that touch the backend (vs pure transforms like
    # jax.jit/jax.grad, which only wrap)
    _JAX_DEVICE = {"random", "numpy", "device_put", "devices",
                   "local_devices", "device_count", "local_device_count",
                   "make_array_from_callback",
                   "make_array_from_single_device_arrays"}
    _JNP_OK = {"dtype"}          # metadata-only, no backend touch

    def check(self, tree, lines, path):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _enclosing(node, FUNC_TYPES)
            if fn is not None:
                # a default-argument expression evaluates when the def
                # executes — import time for a top-level def; anything
                # else inside a function runs at call time
                if not self._in_defaults(node) \
                        or _enclosing(fn, FUNC_TYPES) is not None:
                    continue
            chain = _dotted(node.func)
            if not chain:
                continue
            flagged = None
            if chain[0] == "jnp" and len(chain) > 1 \
                    and chain[1] not in self._JNP_OK:
                flagged = ".".join(chain)
            elif chain[0] == "jax" and len(chain) > 1 \
                    and chain[1] in self._JAX_DEVICE:
                flagged = ".".join(chain)
            if flagged is None:
                continue
            where = ("default argument" if self._in_defaults(node)
                     else "module import scope")
            out.append(self._f(
                path, node,
                f"`{flagged}(...)` at {where} executes device work at "
                f"import — move it inside the function (or make it lazy)"))
        return out

    @staticmethod
    def _in_defaults(node: ast.AST) -> bool:
        for p in _parents(node):
            if isinstance(p, ast.arguments):
                return True
            if isinstance(p, (ast.stmt,)):
                return False
        return False


# --------------------------------------------------------------------------
class NonAtomicPointerWrite(Rule):
    id = "DS009"
    name = "non-atomic-pointer-write"
    autofixable = False
    rationale = ("replacing a pointer/marker file (`latest`-style) with a "
                 "plain open(..., 'w').write is not atomic — a crash "
                 "mid-write leaves a torn pointer every loader resolves as "
                 "garbage; write a tmp file, fsync, then os.replace "
                 "(runtime/checkpointing._atomic_write_text is the clean "
                 "shape)")

    # pointer-ish identifiers/literals: the files whose torn state takes
    # the whole checkpoint dir down (vs payload files, which the
    # manifest validation catches)
    _POINTER = re.compile(r"latest|pointer|marker", re.IGNORECASE)
    _TEMP = re.compile(r"te?mp", re.IGNORECASE)
    # the rule only applies to checkpoint-layer files: that's where a
    # torn pointer is load-bearing, and where the repo has actually
    # shipped the bug (pre-robustness save_checkpoint)
    _PATHS = re.compile(r"checkpoint|ckpt", re.IGNORECASE)
    _ATOMIC = (["os", "replace"], ["os", "rename"])

    def check(self, tree, lines, path):
        if not self._PATHS.search(path.replace("\\", "/")):
            return []
        out = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _dotted(node.func) == ["open"] and node.args):
                continue
            if not self._write_mode(node):
                continue
            target = node.args[0]
            if not self._mentions(target, self._POINTER) \
                    or self._mentions(target, self._TEMP):
                continue
            scope = _enclosing(node, FUNC_TYPES) or tree
            if self._has_atomic_replace(scope):
                continue
            out.append(self._f(
                path, node,
                "pointer/marker file written in place — a crash mid-write "
                "tears it for every future load; write to a tmp path and "
                "os.replace() into place (+ fsync)"))
        return out

    @staticmethod
    def _write_mode(call: ast.Call) -> bool:
        mode = None
        if len(call.args) > 1:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        return (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and ("w" in mode.value or "a" in mode.value))

    @staticmethod
    def _mentions(target: ast.AST, pat) -> bool:
        for n in ast.walk(target):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and pat.search(n.value):
                return True
            if isinstance(n, ast.Name) and pat.search(n.id):
                return True
            if isinstance(n, ast.Attribute) and pat.search(n.attr):
                return True
        return False

    def _has_atomic_replace(self, scope: ast.AST) -> bool:
        for n in ast.walk(scope):
            if isinstance(n, FUNC_TYPES) and n is not scope:
                continue   # walk still descends; acceptable over-approx
            if isinstance(n, ast.Call) and _dotted(n.func) in self._ATOMIC:
                return True
        return False


# --------------------------------------------------------------------------
class UnseededRandomness(Rule):
    id = "DS010"
    name = "unseeded-randomness"
    autofixable = False
    rationale = ("the inference layer's reproducibility contracts "
                 "(per-request key chains, evict/requeue and router-drain "
                 "bit-parity, spec-verify replay) all assume every random "
                 "draw is a pure function of an explicit seed; a "
                 "process-global np.random draw or a PRNGKey minted from "
                 "wall-clock/os entropy silently breaks replay the first "
                 "time a request resumes on a different engine")

    # only the inference layer carries the replay contracts; training
    # scripts legitimately want ambient-seeded data order
    _PATHS = re.compile(r"(^|/)deepspeed_tpu/inference/")
    # explicitly-seeded numpy constructs (the sanctioned shapes)
    _SEEDED = {"default_rng", "Generator", "SeedSequence", "Philox",
               "PCG64", "MT19937"}
    _ENTROPY = (["time", "time"], ["time", "time_ns"],
                ["time", "perf_counter"], ["time", "monotonic"],
                ["os", "urandom"], ["os", "getrandom"],
                ["uuid", "uuid4"], ["random", "random"],
                ["random", "randint"], ["random", "getrandbits"],
                ["secrets", "randbits"], ["secrets", "token_bytes"])

    def check(self, tree, lines, path):
        if not self._PATHS.search(path.replace("\\", "/")):
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if len(chain) == 3 and chain[0] in ("np", "numpy") \
                    and chain[1] == "random":
                tail = chain[2]
                if tail == "RandomState":
                    if not (node.args or node.keywords):
                        out.append(self._f(
                            path, node,
                            "`np.random.RandomState()` with no seed draws "
                            "from os entropy — pass an explicit seed (or "
                            "use np.random.default_rng(seed))"))
                elif tail not in self._SEEDED:
                    out.append(self._f(
                        path, node,
                        f"`{'.'.join(chain)}` uses the process-global "
                        f"numpy RNG — inference replay (evict/requeue, "
                        f"router drain) needs an explicit "
                        f"np.random.default_rng(seed)/Generator"))
            elif chain[-2:] in (["random", "PRNGKey"], ["random", "key"]) \
                    and chain[0] in ("jax", "jr"):
                if any(isinstance(n, ast.Call)
                       and _dotted(n.func) in self._ENTROPY
                       for a in node.args + [kw.value
                                             for kw in node.keywords]
                       for n in ast.walk(a)):
                    out.append(self._f(
                        path, node,
                        "`jax.random.PRNGKey` seeded from ambient entropy "
                        "(time/os/random) — thread an explicit request or "
                        "config seed so the key chain replays"))
        return out


# --------------------------------------------------------------------------

def default_rules() -> List[Rule]:
    return [BlockingHostSync(), JitCacheFragmentation(), DonationHazard(),
            TracedPythonBranch(), EnvReadOutsideConfig(), OverbroadExcept(),
            MutableDefaultArg(), ImportScopeDeviceWork(),
            NonAtomicPointerWrite(), UnseededRandomness()]


def rule_catalog() -> List[Dict[str, str]]:
    return [{"id": r.id, "name": r.name,
             "autofixable": r.autofixable, "rationale": r.rationale}
            for r in default_rules()]
