"""``python -m tools.dslint --explain DS0NN`` — print one rule's
documentation plus a minimal true-positive example.

The examples double as living documentation of what each rule actually
fires on: every snippet here is the smallest program that trips its
rule, written in the repo's own idiom. (They are illustrative text, not
fixtures — the executable fixtures live in tests/.)
"""

from typing import Dict, Optional

EXAMPLES: Dict[str, str] = {
    "DS001": """\
x = jnp.zeros((4, 4))
for i in range(4):
    x = x.at[i].set(i)          # DS001: per-element .at[] in a python
                                # loop — one dispatch per element""",
    "DS002": """\
step = jax.jit(lambda p, x, flag: p * x if flag else x)
# DS002: `flag` selects a branch but is not in static_argnums/names""",
    "DS003": """\
step = jax.jit(update, donate_argnums=(0,))
new = step(params, grads)
loss = compute(params)          # DS003: `params` used after donation""",
    "DS004": """\
@partial(jax.jit)
def f(x):
    if x > 0:                   # DS004: python branch on a traced value
        return x
    return -x""",
    "DS005": """\
def choose_impl():
    return os.environ.get("DS_ATTN_IMPL", "gather")
# DS005: env read outside utils/env.py's registered-flag layer""",
    "DS006": """\
result = jax.device_get(x)
y = compute(result)
z = jax.device_get(y)           # DS006: sync inside the hot loop""",
    "DS007": """\
@partial(jax.jit)
def f(x):
    print("tracing", x)         # DS007: host side effect under trace""",
    "DS008": """\
pool = jnp.zeros((L, N, B, H, D))
pool2 = pool + 0                # DS008: whole-pool copy on the serving
                                # path — doubles HBM transiently""",
    "DS009": """\
def step(self, tokens):
    return self._decode(np.asarray(tokens))
# DS009: host array fed straight to a jitted call per step —
# re-uploads every dispatch""",
    "DS010": """\
key = jax.random.PRNGKey(0)
for _ in range(n):
    tok = sample(key)           # DS010: key reused — identical draws""",
    "DS011": """\
step = jax.jit(update, donate_argnums=(0,))   # donates params


def caller(params, grads):
    new = step(params, grads)
    return params, new          # DS011: caller keeps the donated ref""",
    "DS012": """\
def cow(self, src, dst):
    # fault site "cache.cow" is in FAULT_SITES but no maybe_fire
    # ever names it on this path  -> DS012 (integrity direction)
    return self._cow_blocks(src, dst)""",
    "DS013": """\
impl = os.environ.get("DS_NEW_KNOB")   # DS013: flag read but never
                                       # declared in utils/env.py""",
    "DS014": """\
self._m = Counter("serving_new_metric")   # DS014: registered metric
# missing from tools/dslint/telemetry_schema.json""",
    "DS015": """\
def _decode_slots_fn(self, params, k_pool, v_pool, tokens):
    x = embed(params, tokens)
    x = x + positional(params, tokens)      # <- edited in base only
    return project(params, x), k_pool, v_pool


def _decode_slots_q_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                       tokens):
    x = embed(params, tokens)
    # DS015: the positional-embedding statement above is missing here
    # and `k_scale`/`v_scale` don't excuse it — the q delta
    # (jit_registry.TWIN_DELTAS["q"]) only owns the scale sidecars
    return project(params, x), k_pool, v_pool, k_scale, v_scale""",
    "DS016": """\
def admit(self, rid, n):
    slot = self.cache.allocate(rid, n)
    if self.adapters is not None:
        row = self.adapters.acquire(rid)    # may raise
        # DS016: on the exception edge out of acquire(), `slot`
        # reaches function exit without cache.free(slot) — leaked
    self.slots[rid] = slot""",
    "DS017": """\
@partial(jax.jit)
def f(x):
    y = x * 2
    flag = y.sum()
    if flag > 0:                # DS017: branch on `flag`, which derives
        return y                # from traced `x` via assignments —
    return -y                   # DS004 can't see through the chain""",
    "DS018": """\
@dataclass
class ServeRequest:
    rid: str
    retries: int = 0            # DS018: written by the scheduler but
                                # absent from snapshot_entry() and not
                                # declared in SNAPSHOT_EPHEMERAL


def snapshot_entry(req):
    return {"rid": req.rid}""",
}


def explain(rule_id: str) -> Optional[str]:
    """Formatted doc + minimal TP example for one rule id, or None when
    the id is unknown."""
    from tools.dslint.interproc import interproc_catalog
    from tools.dslint.rules import rule_catalog
    rule_id = rule_id.strip().upper()
    entry = next((r for r in rule_catalog() + interproc_catalog()
                  if r["id"] == rule_id), None)
    if entry is None:
        return None
    fix = " [autofixable]" if entry["autofixable"] else ""
    lines = [f"{entry['id']} — {entry['name']}{fix}", "",
             entry["rationale"], ""]
    example = EXAMPLES.get(rule_id)
    if example:
        lines.append("minimal true positive:")
        lines.append("")
        lines.extend("    " + l for l in example.splitlines())
        lines.append("")
    lines.append(f"docs: docs/LINT.md; suppress with "
                 f"`# dslint: disable={rule_id} — <reason>`")
    return "\n".join(lines)
