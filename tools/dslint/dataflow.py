"""dslint v3: per-function control-flow graphs, forward dataflow, and
the flow-sensitive rules DS015–DS018.

The v2 interprocedural layer (:mod:`interproc`) sees *across* modules
but not *through* control flow — it cannot tell "released on every
path" from "released on the happy path". This module adds the missing
layer:

- :func:`build_cfg` — a per-function CFG with branch, loop (incl.
  for-else/while-else), try/except/finally, break/continue, raise and
  early-return edges. Statements inside a ``try`` body get one block
  each so exception edges are per-statement.
- :class:`ForwardAnalysis` / :class:`GenKill` + :func:`solve_forward` —
  a generic forward worklist solver over set-valued facts (union join,
  monotone transfer ⇒ the fixpoint terminates).
- :func:`build_pair_summaries` — interprocedural acquire/release
  summaries riding the PR-14 symbol table, so lifecycle-split helpers
  (``spill_tick`` acquires, ``_harvest_spill`` releases) are checked as
  a package, not per function.

The rules on top:

DS015  jit-twin drift: every registered twin family
       (``jit_registry.ENGINE_PROGRAM_FAMILIES``) must match its base
       program statement-for-statement after normalizing away the
       feature's DECLARED delta (``jit_registry.TWIN_DELTAS``) — an
       edit to ``_decode_slots_fn`` that misses ``_decode_slots_q_fn``
       is a lint error, not a silent parity bug.
DS016  resource pairing: path-sensitive acquire/release balance for
       the repo's paired APIs (block allocate/free, adapter
       acquire/release, ``_in_transfer`` add/discard, host-tier
       pin/abort) — paths (including exception edges) that leak a
       local handle or double-release flag, plus a package-wide
       "acquired somewhere but released nowhere" summary direction.
DS017  traced-value escape: dataflow taint from traced jit arguments
       through assignment chains into Python control flow, host-sync
       calls, or dict keys — the flow-sensitive superset of the purely
       syntactic DS004 (DS017 only reports what DS004 cannot see, so
       the two never double-report one site).
DS018  snapshot round-trip completeness: every dataclass field of a
       snapshot-bearing request type (``ServeRequest``) must be
       serialized by ``snapshot_entry`` AND restored by
       ``from_snapshot`` — or be declared ephemeral in the module's
       ``SNAPSHOT_EPHEMERAL`` allowlist (adapter_id, seed chains and
       cost footprints each had to be retrofitted in separate PRs;
       this makes the next field a lint error instead).

Like every dslint rule, these never import the code under analysis:
the twin delta spec is loaded from ``utils/jit_registry.py`` by file
path, exactly like the jit wrapper chains in :mod:`symbols`.
"""

import ast
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import (Dict, FrozenSet, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from tools.dslint.core import REPO_ROOT, Finding
from tools.dslint.interproc import InterprocRule, _dedupe
from tools.dslint.rules import FUNC_TYPES, TracedPythonBranch, _dotted
from tools.dslint.symbols import FuncInfo, SymbolTable

# ==========================================================================
# control-flow graph
# ==========================================================================

NORMAL = "normal"
EXC = "exc"            # exception edge (try-body stmt -> handler/finally)


class Block:
    """A straight-line run of statements. ``succ`` maps successor block
    -> edge kind (``normal`` | ``exc``)."""

    __slots__ = ("id", "label", "stmts", "succ", "pred")

    def __init__(self, bid: int, label: str = ""):
        self.id = bid
        self.label = label
        self.stmts: List[ast.stmt] = []
        self.succ: Dict["Block", str] = {}
        self.pred: Dict["Block", str] = {}

    def __repr__(self):
        return f"B{self.id}({self.label or len(self.stmts)})"

    def __hash__(self):
        return self.id


class CFG:
    """Control-flow graph of one function body: unique ``entry`` and
    ``exit`` blocks; ``exit`` doubles as the exceptional exit (an
    uncaught raise flows there too)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.blocks: List[Block] = []
        self.entry = self.new("entry")
        self.exit = self.new("exit")

    def new(self, label: str = "") -> Block:
        b = Block(len(self.blocks), label)
        self.blocks.append(b)
        return b

    def edge(self, src: Optional[Block], dst: Block,
             kind: str = NORMAL) -> None:
        if src is None:
            return
        src.succ.setdefault(dst, kind)
        dst.pred.setdefault(src, kind)


class _Builder:
    def __init__(self, fn: ast.AST):
        self.cfg = CFG(fn)
        # (continue_target, break_target) innermost-last
        self.loops: List[Tuple[Block, Block]] = []
        # innermost-last list of exception targets: the blocks an
        # exception raised "here" may reach (handler entries + finally)
        self.exc: List[List[Block]] = []
        # innermost-last finally entries (return/break route through)
        self.finals: List[Block] = []

    def build(self) -> CFG:
        end = self._stmts(self.cfg.fn.body, self.cfg.entry)
        self.cfg.edge(end, self.cfg.exit)
        return self.cfg

    # -- statement dispatch ---------------------------------------------

    def _stmts(self, body: Sequence[ast.stmt],
               cur: Optional[Block]) -> Optional[Block]:
        """Process a statement list starting in ``cur``; returns the
        block control falls out of, or None when the end is
        unreachable (every path returned/raised/broke)."""
        for stmt in body:
            if cur is None:
                # dead code after return/raise: give it its own island
                # so analyses stay total, but nothing flows in
                cur = self.cfg.new("dead")
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(stmt, ast.If):
            return self._if(stmt, cur)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, cur)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            cur.stmts.append(stmt)
            return self._stmts(stmt.body, cur)
        if isinstance(stmt, ast.Return):
            cur.stmts.append(stmt)
            self.cfg.edge(cur, self.finals[-1] if self.finals
                          else self.cfg.exit)
            return None
        if isinstance(stmt, ast.Raise):
            cur.stmts.append(stmt)
            targets = self.exc[-1] if self.exc else [self.cfg.exit]
            for t in targets:
                self.cfg.edge(cur, t, EXC)
            return None
        if isinstance(stmt, ast.Break):
            cur.stmts.append(stmt)
            if self.loops:
                self.cfg.edge(cur, self.loops[-1][1])
            return None
        if isinstance(stmt, ast.Continue):
            cur.stmts.append(stmt)
            if self.loops:
                self.cfg.edge(cur, self.loops[-1][0])
            return None
        # plain statement (incl. nested defs, which are opaque here)
        cur.stmts.append(stmt)
        if self.exc:
            # inside a try body: per-statement exception edges — end the
            # block so the edge is as precise as the statement
            for t in self.exc[-1]:
                self.cfg.edge(cur, t, EXC)
            nxt = self.cfg.new()
            self.cfg.edge(cur, nxt)
            return nxt
        return cur

    def _if(self, stmt: ast.If, cur: Block) -> Optional[Block]:
        cur.stmts.append(stmt)      # the test evaluates in cur
        after = self.cfg.new("endif")
        then_b = self.cfg.new("then")
        self.cfg.edge(cur, then_b)
        then_end = self._stmts(stmt.body, then_b)
        self.cfg.edge(then_end, after)
        if stmt.orelse:
            else_b = self.cfg.new("else")
            self.cfg.edge(cur, else_b)
            else_end = self._stmts(stmt.orelse, else_b)
            self.cfg.edge(else_end, after)
        else:
            self.cfg.edge(cur, after)
        return after if after.pred else None

    def _loop(self, stmt, cur: Block) -> Optional[Block]:
        header = self.cfg.new("loop")
        header.stmts.append(stmt)   # test / iter evaluates per entry
        self.cfg.edge(cur, header)
        after = self.cfg.new("endloop")
        body_b = self.cfg.new("body")
        self.cfg.edge(header, body_b)
        self.loops.append((header, after))
        body_end = self._stmts(stmt.body, body_b)
        self.cfg.edge(body_end, header)     # back edge
        self.loops.pop()
        if stmt.orelse:
            # else runs on NORMAL loop exit (no break)
            else_b = self.cfg.new("loopelse")
            self.cfg.edge(header, else_b)
            else_end = self._stmts(stmt.orelse, else_b)
            self.cfg.edge(else_end, after)
        else:
            self.cfg.edge(header, after)
        return after if after.pred else None

    def _try(self, stmt: ast.Try, cur: Block) -> Optional[Block]:
        after = self.cfg.new("endtry")
        fin_entry = self.cfg.new("finally") if stmt.finalbody else None
        handler_entries = [self.cfg.new("except") for _ in stmt.handlers]
        # exception targets while inside the try body: every handler may
        # match; with no handlers the finally is the only catcher
        targets = list(handler_entries) or \
            ([fin_entry] if fin_entry else [])
        if stmt.handlers and fin_entry is not None:
            # an exception no handler matches still runs the finally
            targets = targets + [fin_entry]
        self.exc.append(targets or [self.cfg.exit])
        if fin_entry is not None:
            self.finals.append(fin_entry)
        body_b = self.cfg.new("try")
        self.cfg.edge(cur, body_b)
        body_end = self._stmts(stmt.body, body_b)
        self.exc.pop()
        else_end = self._stmts(stmt.orelse, body_end) \
            if stmt.orelse else body_end
        normal_join = fin_entry if fin_entry is not None else after
        self.cfg.edge(else_end, normal_join)
        for hb, handler in zip(handler_entries, stmt.handlers):
            h_end = self._stmts(handler.body, hb)
            self.cfg.edge(h_end, normal_join)
        if fin_entry is not None:
            self.finals.pop()
            fin_end = self._stmts(stmt.finalbody, fin_entry)
            if fin_end is not None:
                self.cfg.edge(fin_end, after)
                # the finally also forwards in-flight returns/raises
                outer = self.exc[-1] if self.exc else [self.cfg.exit]
                for t in outer:
                    self.cfg.edge(fin_end, t, EXC)
        return after if after.pred else None


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one function/method body (``fn`` is a FunctionDef)."""
    return _Builder(fn).build()


# ==========================================================================
# forward dataflow
# ==========================================================================

class ForwardAnalysis:
    """Forward may-analysis over frozenset facts: union join. Subclass
    and override :meth:`transfer_stmt` (or use :class:`GenKill`)."""

    def boundary(self) -> FrozenSet:
        return frozenset()

    def join(self, facts: Iterable[FrozenSet]) -> FrozenSet:
        out: FrozenSet = frozenset()
        for f in facts:
            out = out | f
        return out

    def transfer_stmt(self, stmt: ast.stmt, fact: FrozenSet) -> FrozenSet:
        return fact

    def transfer_block(self, block: Block, fact: FrozenSet) -> FrozenSet:
        for s in block.stmts:
            fact = self.transfer_stmt(s, fact)
        return fact


class GenKill(ForwardAnalysis):
    """gen/kill convenience: ``out = (in - kill(stmt)) | gen(stmt)``."""

    def gen(self, stmt: ast.stmt, fact: FrozenSet) -> Iterable:
        return ()

    def kill(self, stmt: ast.stmt, fact: FrozenSet) -> Iterable:
        return ()

    def transfer_stmt(self, stmt, fact):
        return (fact - frozenset(self.kill(stmt, fact))) \
            | frozenset(self.gen(stmt, fact))


def solve_forward(cfg: CFG, analysis: ForwardAnalysis
                  ) -> Tuple[Dict[Block, FrozenSet], Dict[Block, FrozenSet]]:
    """Worklist fixpoint; returns (in_facts, out_facts) per block.
    Monotone transfers over a finite fact lattice converge (loops
    included — the back edge just re-queues the header until stable)."""
    in_facts: Dict[Block, FrozenSet] = {}
    out_facts: Dict[Block, FrozenSet] = {}
    work = deque(cfg.blocks)
    while work:
        b = work.popleft()
        preds = [out_facts.get(p, frozenset()) for p in b.pred]
        inf = analysis.join(preds)
        if b is cfg.entry:
            inf = inf | analysis.boundary()
        out = analysis.transfer_block(b, inf)
        in_facts[b] = inf
        if out != out_facts.get(b):
            out_facts[b] = out
            for s in b.succ:
                if s not in work:
                    work.append(s)
    return in_facts, out_facts


# ==========================================================================
# shared AST helpers
# ==========================================================================

def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def shallow_walk(stmt: ast.stmt):
    """Walk a CFG-block statement's HEADER only. Compound statements
    land in a block alongside their test/iter/items, but their nested
    bodies live in their own blocks — a transfer function that walked
    the whole subtree would count every nested call twice (once in the
    header block, once in the body block). Nested function bodies
    don't execute here at all, so defs are opaque."""
    if isinstance(stmt, (ast.If, ast.While)):
        yield from ast.walk(stmt.test)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield from ast.walk(stmt.target)
        yield from ast.walk(stmt.iter)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield from ast.walk(item.context_expr)
            if item.optional_vars is not None:
                yield from ast.walk(item.optional_vars)
    elif isinstance(stmt, (ast.Try, *FUNC_TYPES, ast.ClassDef)):
        yield stmt
    else:
        yield from ast.walk(stmt)


def _call_chain(call: ast.Call) -> List[str]:
    return _dotted(call.func)


def _fn_params(fn: ast.AST) -> List[str]:
    return [a.arg for a in (list(fn.args.posonlyargs) + list(fn.args.args)
                            + list(fn.args.kwonlyargs))]


# ==========================================================================
# DS015 — jit-twin drift
# ==========================================================================

_FALLBACK_FAMILIES: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
_FALLBACK_DELTAS: Dict[str, Dict[str, Tuple[str, ...]]] = {}
_TWIN_SPEC_CACHE: Optional[Tuple[tuple, dict]] = None


def load_twin_spec() -> Tuple[tuple, dict]:
    """(ENGINE_PROGRAM_FAMILIES, TWIN_DELTAS) from utils/jit_registry.py,
    loaded from the FILE path (dslint never imports the code under
    analysis). Cached; empty spec when the registry is absent or
    predates TWIN_DELTAS (fixture trees)."""
    global _TWIN_SPEC_CACHE
    if _TWIN_SPEC_CACHE is not None:
        return _TWIN_SPEC_CACHE
    path = REPO_ROOT / "deepspeed_tpu" / "utils" / "jit_registry.py"
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_ds_jit_registry_v3",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TWIN_SPEC_CACHE = (
            tuple((stem, tuple(sufs))
                  for stem, sufs in mod.ENGINE_PROGRAM_FAMILIES),
            {k: {kk: tuple(vv) for kk, vv in v.items()}
             for k, v in mod.TWIN_DELTAS.items()})
    except Exception:
        _TWIN_SPEC_CACHE = (_FALLBACK_FAMILIES, _FALLBACK_DELTAS)
    return _TWIN_SPEC_CACHE


def _delta_union(features: Sequence[str],
                 deltas: Dict[str, Dict[str, Tuple[str, ...]]]
                 ) -> Tuple[Set[str], Set[str], Set[str]]:
    """(owned params, owned names, owned kwargs) for a twin suffix's
    feature characters (``"_ql"`` → features ``("q", "l")``)."""
    params: Set[str] = set()
    names: Set[str] = set()
    kwargs: Set[str] = set()
    for f in features:
        d = deltas.get(f, {})
        params |= set(d.get("params", ()))
        names |= set(d.get("params", ())) | set(d.get("names", ()))
        kwargs |= set(d.get("kwargs", ()))
    return params, names, kwargs


class _TwinNormalizer:
    """Renders a function AST to per-statement fingerprints with the
    feature-owned delta stripped: owned parameters disappear from the
    signature, owned tuple/call elements and keywords disappear from
    expressions, and statements that only bind owned names disappear
    entirely. A base program normalizes with an empty delta, so base
    and twin compare statement-for-statement."""

    _POS_FIELDS = ("lineno", "col_offset", "end_lineno", "end_col_offset",
                   "type_comment")

    def __init__(self, owned_names: Set[str], owned_kwargs: Set[str]):
        self.names = owned_names
        self.kwargs = owned_kwargs

    def _owned(self, node: ast.AST) -> bool:
        used = _names_in(node)
        return bool(used & self.names)

    def signature(self, fn: ast.AST, owned_params: Set[str]) -> str:
        args = [a for a in (list(fn.args.posonlyargs) + list(fn.args.args))
                if a.arg not in owned_params]
        # align defaults to their params before filtering
        all_args = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = [None] * (len(all_args) - len(fn.args.defaults)) \
            + list(fn.args.defaults)
        by_name = {a.arg: d for a, d in zip(all_args, defaults)}
        parts = []
        for a in args:
            d = by_name.get(a.arg)
            parts.append(a.arg + ("=" + self.render(d)
                                  if d is not None else ""))
        return "(" + ", ".join(parts) + ")"

    def body_fps(self, fn: ast.AST) -> List[Tuple[str, int]]:
        """(fingerprint, lineno) per surviving top-level statement;
        the leading docstring never counts."""
        out: List[Tuple[str, int]] = []
        for i, stmt in enumerate(fn.body):
            if i == 0 and isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                continue
            fp = self.render_stmt(stmt)
            if fp is not None:
                out.append((fp, stmt.lineno))
        return out

    # -- rendering ------------------------------------------------------

    def render_stmt(self, stmt: ast.stmt) -> Optional[str]:
        """Fingerprint of one statement, or None when the whole
        statement is feature-owned (all its bound names are owned)."""
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            kept = [self._clean_target(t) for t in targets]
            if all(k is None for k in kept):
                return None
            tgt = ",".join(k for k in kept if k is not None)
            val = self.render(stmt.value) if stmt.value is not None else ""
            op = type(stmt.op).__name__ if isinstance(
                stmt, ast.AugAssign) else "="
            return f"Assign[{tgt} {op} {val}]"
        return self.render(stmt)

    def _clean_target(self, t: ast.AST) -> Optional[str]:
        """Render an assignment target with owned names dropped at any
        tuple-nesting depth; None when nothing survives."""
        if isinstance(t, ast.Name):
            return None if t.id in self.names else t.id
        if isinstance(t, (ast.Tuple, ast.List)):
            kept = [self._clean_target(e) for e in t.elts]
            kept = [k for k in kept if k is not None]
            if not kept:
                return None
            return "(" + ",".join(kept) + ")"
        if isinstance(t, ast.Starred):
            inner = self._clean_target(t.value)
            return None if inner is None else "*" + inner
        return self.render(t)

    def _clean_elts(self, elts: Sequence[ast.AST]) -> List[str]:
        """Container elements / call arguments with feature-owned ones
        dropped. Containers recurse (a mixed scan-operand tuple keeps
        its shared elements); a non-container element that mentions ANY
        owned name is feature-owned and dropped — safe, because a base
        body by construction never mentions an owned name, so nothing
        is ever dropped from the base side."""
        out: List[str] = []
        for e in elts:
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                out.append(self.render(e))
            elif not self._owned(e):
                out.append(self.render(e))
        return out

    def render(self, node) -> str:
        if node is None:
            return "None"
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return (type(node).__name__ + "["
                    + ",".join(self._clean_elts(node.elts)) + "]")
        if isinstance(node, ast.Call):
            kws = [k for k in node.keywords
                   if not (k.arg in self.kwargs
                           or (k.arg is None and self._owned(k.value)))]
            return ("Call[" + self.render(node.func) + "]("
                    + ",".join(self._clean_elts(node.args)) + ")("
                    + ",".join(f"{k.arg}={self.render(k.value)}"
                               for k in kws) + ")")
        if isinstance(node, ast.Constant):
            return f"Const[{node.value!r}]"
        if isinstance(node, ast.Name):
            return f"Name[{node.id}]"
        if isinstance(node, ast.AST):
            parts = []
            for fname, val in ast.iter_fields(node):
                if fname in self._POS_FIELDS or fname == "ctx":
                    continue
                parts.append(fname + "=" + self._render_field(val))
            return type(node).__name__ + "(" + ",".join(parts) + ")"
        return repr(node)

    def _render_field(self, val) -> str:
        if isinstance(val, list):
            if val and isinstance(val[0], ast.stmt):
                fps = [self.render_stmt(s) for s in val]
                return "[" + ";".join(f for f in fps if f is not None) + "]"
            return "[" + ";".join(self._render_field(v) for v in val) + "]"
        if isinstance(val, ast.AST):
            return self.render(val)
        return repr(val)


class JitTwinDrift(InterprocRule):
    id = "DS015"
    name = "jit-twin-drift"
    autofixable = False
    rationale = ("the engine hand-maintains a 2^n family of jit twins "
                 "(_q/_l/_ql per program); an edit to the base body that "
                 "misses a twin is a silent numerics/parity bug — twins "
                 "must match the base statement-for-statement modulo the "
                 "feature delta DECLARED in jit_registry.TWIN_DELTAS")

    def __init__(self, spec: Optional[Tuple[tuple, dict]] = None):
        self._spec = spec       # (families, deltas) override for tests

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        families, deltas = self._spec if self._spec is not None \
            else load_twin_spec()
        if not families:
            return []
        by_name: Dict[str, List[FuncInfo]] = {}
        for fn in table.functions:
            by_name.setdefault(fn.name, []).append(fn)
        out: List[Finding] = []
        for stem, suffixes in families:
            bases = by_name.get(f"_{stem}_fn", ())
            for base in bases:
                if base.node is None:
                    continue
                norm0 = _TwinNormalizer(set(), set())
                base_sig = norm0.signature(base.node, {"self", "cls"})
                base_fps = norm0.body_fps(base.node)
                for suf in suffixes:
                    if not suf:
                        continue
                    # both twin spellings in use: engine methods say
                    # `_decode_slots_q_fn`, paged_cache module-level
                    # defaults say `_gather_blocks_fn_q`
                    twin_name = f"_{stem}{suf}_fn"
                    twins = [t for t in (list(by_name.get(twin_name, ()))
                                         + list(by_name.get(
                                             f"_{stem}_fn{suf}", ())))
                             if t.path == base.path and t.node is not None]
                    if not twins:
                        if not partial:
                            out.append(self._f(
                                base.path, base.line,
                                f"twin family '{stem}' registers suffix "
                                f"'{suf}' in ENGINE_PROGRAM_FAMILIES but "
                                f"`{twin_name}` is not defined — the "
                                f"program catalog and the engine "
                                f"disagree"))
                        continue
                    features = list(suf.lstrip("_"))
                    owned_p, owned_n, owned_k = _delta_union(features,
                                                             deltas)
                    norm = _TwinNormalizer(owned_n, owned_k)
                    for twin in twins:
                        out.extend(self._compare(
                            base, base_sig, base_fps, twin,
                            norm.signature(twin.node,
                                           owned_p | {"self", "cls"}),
                            norm.body_fps(twin.node), suf))
        return _dedupe(out)

    def _compare(self, base: FuncInfo, base_sig: str,
                 base_fps: List[Tuple[str, int]], twin: FuncInfo,
                 twin_sig: str, twin_fps: List[Tuple[str, int]],
                 suf: str) -> List[Finding]:
        what = (f"`{twin.name}` drifts from `{base.name}` outside the "
                f"declared '{suf.lstrip('_')}' delta")
        fix = ("edit base and twin together, or extend "
               "jit_registry.TWIN_DELTAS if the divergence is a new "
               "feature-owned shape")
        if twin_sig != base_sig:
            return [self._f(
                twin.path, twin.line,
                f"{what}: signature {twin_sig} != base {base_sig} after "
                f"stripping feature-owned parameters — {fix}")]
        out: List[Finding] = []
        for i, ((bfp, bline), (tfp, tline)) in enumerate(
                zip(base_fps, twin_fps)):
            if bfp != tfp:
                out.append(self._f(
                    twin.path, tline,
                    f"{what}: statement {i + 1} does not match the base "
                    f"statement at {base.path}:{bline} — {fix}"))
                return out
        if len(twin_fps) < len(base_fps):
            bline = base_fps[len(twin_fps)][1]
            out.append(self._f(
                twin.path, twin.line,
                f"{what}: base statement at {base.path}:{bline} has no "
                f"counterpart in the twin — {fix}"))
        elif len(twin_fps) > len(base_fps):
            tline = twin_fps[len(base_fps)][1]
            out.append(self._f(
                twin.path, tline,
                f"{what}: twin statement at line {tline} has no "
                f"counterpart in the base — {fix}"))
        return out


# ==========================================================================
# DS016 — resource pairing
# ==========================================================================

@dataclass(frozen=True)
class PairSpec:
    """One paired acquire/release API. ``handle=True`` pairs return a
    trackable handle from the acquire (``bid = cache.allocate(...)``);
    set-style pairs (``handle=False``) mutate a named container attr
    (``self._in_transfer.update(ids)``) and are checked by package-wide
    summary balance instead of per-path handles."""
    kind: str
    acquire: Tuple[str, ...]
    release: Tuple[str, ...]
    handle: bool = True
    attr_suffix: Optional[str] = None    # receiver constraint (set-style)


DEFAULT_PAIRS: Tuple[PairSpec, ...] = (
    PairSpec("cache-block", ("allocate",), ("free", "_release")),
    PairSpec("adapter", ("acquire",), ("release",)),
    PairSpec("in-transfer", ("add", "update"), ("discard", "remove"),
             handle=False, attr_suffix="_in_transfer"),
    PairSpec("host-pin", ("pin",), ("unpin", "abort")),
)


def _calls_in(fn_node: ast.AST) -> List[Tuple[List[str], ast.Call]]:
    """All (dotted chain, Call) pairs under ``fn_node``, computed once
    per node — DS016 consults this list once per pair spec and again
    per check direction, so the walk itself must not repeat."""
    cached = getattr(fn_node, "_ds_calls", None)
    if cached is None:
        cached = [(_call_chain(n), n) for n in ast.walk(fn_node)
                  if isinstance(n, ast.Call)]
        cached = [(c, n) for c, n in cached if c]
        fn_node._ds_calls = cached
    return cached


def _pair_calls(fn_node: ast.AST, spec: PairSpec
                ) -> Tuple[List[ast.Call], List[ast.Call]]:
    """(acquire calls, release calls) of one pair inside ``fn_node``."""
    acq: List[ast.Call] = []
    rel: List[ast.Call] = []
    for chain, n in _calls_in(fn_node):
        if spec.attr_suffix is not None:
            # set-style: <...>._in_transfer.<op>(...)
            if len(chain) < 2 or not chain[-2].endswith(spec.attr_suffix):
                continue
        if chain[-1] in spec.acquire:
            acq.append(n)
        elif chain[-1] in spec.release:
            rel.append(n)
    return acq, rel


@dataclass
class PairSummary:
    """Interprocedural summary of one function's net pair activity:
    how many acquire and release sites of each kind it contains
    (transitively local — helpers are their own summaries)."""
    acquires: Dict[str, int] = field(default_factory=dict)
    releases: Dict[str, int] = field(default_factory=dict)


def summarize_pairs(fn_node: ast.AST,
                    pairs: Sequence[PairSpec] = DEFAULT_PAIRS
                    ) -> PairSummary:
    s = PairSummary()
    for spec in pairs:
        acq, rel = _pair_calls(fn_node, spec)
        if acq:
            s.acquires[spec.kind] = len(acq)
        if rel:
            s.releases[spec.kind] = len(rel)
    return s


def build_pair_summaries(table: SymbolTable,
                         pairs: Sequence[PairSpec] = DEFAULT_PAIRS
                         ) -> Dict[Tuple[str, str], PairSummary]:
    """(path, function name) -> :class:`PairSummary` for every function
    in the symbol table — the package-wide acquire/release ledger the
    completeness direction of DS016 reads."""
    out: Dict[Tuple[str, str], PairSummary] = {}
    for fn in table.functions:
        if fn.node is None:
            continue
        s = summarize_pairs(fn.node, pairs)
        if s.acquires or s.releases:
            out[(fn.path, fn.name)] = s
    return out


class _ReleasedNames(GenKill):
    """Forward may-analysis: handles released (by pair kind) since
    their last (re)binding — a release while already in the fact is a
    double release on some path."""

    def __init__(self, spec: PairSpec):
        self.spec = spec

    def _released_here(self, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for call in shallow_walk(stmt):
            if isinstance(call, ast.Call):
                h = _release_target(call, self.spec)
                if h:
                    out.add(h)
        return out

    def gen(self, stmt, fact):
        return self._released_here(stmt)

    def kill(self, stmt, fact):
        return _rebound_names(stmt)


def _rebound_names(stmt: ast.stmt) -> Set[str]:
    """Names this statement (header) rebinds: assignment targets,
    for-loop targets, with-as targets."""
    out: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    for t in targets:
        out |= {n.id for n in ast.walk(t)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}
    return out


def _release_target(call: ast.Call, spec: PairSpec) -> Optional[str]:
    """The handle name a release call settles: ``free(h)`` /
    ``pool.release(h)`` → ``h``; ``h.release()`` → ``h``. None when
    ``call`` is not a release of this pair (or the handle isn't a
    simple name)."""
    chain = _call_chain(call)
    if not chain or chain[-1] not in spec.release:
        return None
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    if isinstance(call.func, ast.Attribute) \
            and isinstance(call.func.value, ast.Name) \
            and not call.args:
        return call.func.value.id        # h.release()
    return None


class ResourcePairing(InterprocRule):
    id = "DS016"
    name = "resource-pairing"
    autofixable = False
    rationale = ("the paged cache, adapter pool and host tier all live "
                 "on paired acquire/release discipline (block refcounts, "
                 "adapter pins, in-transfer exclusion); a path — "
                 "including an exception edge — that leaks a handle or "
                 "releases twice corrupts the pool long after the call "
                 "that did it")

    def __init__(self, pairs: Sequence[PairSpec] = DEFAULT_PAIRS):
        self.pairs = tuple(pairs)

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        out: List[Finding] = []
        handle_pairs = [p for p in self.pairs if p.handle]
        for fn in table.functions:
            if fn.node is None:
                continue
            relevant = [p for p in handle_pairs
                        if _pair_calls(fn.node, p) != ([], [])]
            if not relevant:
                continue
            cfg = None
            for spec in relevant:
                acq, rel = _pair_calls(fn.node, spec)
                if not acq:
                    continue
                if cfg is None:
                    cfg = build_cfg(fn.node)
                out.extend(self._check_leaks(fn, cfg, spec, acq))
                out.extend(self._check_double_release(fn, cfg, spec))
        if not partial:
            out.extend(self._check_summary_balance(table))
        return _dedupe(out)

    # -- (a) handle leak: some path from acquire to exit w/o release ----

    def _check_leaks(self, fn: FuncInfo, cfg: CFG, spec: PairSpec,
                     acquires: List[ast.Call]) -> List[Finding]:
        out: List[Finding] = []
        for call in acquires:
            handle = self._handle_of(call, fn.node)
            if handle is None:
                continue
            if self._escapes(fn.node, handle, spec):
                continue
            leak = self._leak_path(cfg, call, handle, spec)
            if leak is not None:
                via = " (via an exception edge)" if leak == EXC else ""
                out.append(self._f(
                    fn.path, call.lineno,
                    f"`{handle}` acquired from `{_call_chain(call)[-1]}` "
                    f"({spec.kind}) is not released on every path to "
                    f"exit{via} — release it on all paths (try/finally) "
                    f"or hand it off explicitly"))
        return out

    @staticmethod
    def _handle_of(call: ast.Call, fn_node: ast.AST) -> Optional[str]:
        """The local name an acquire binds: ``h = pool.acquire(x)``."""
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and n.value is call \
                    and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                return n.targets[0].id
        return None

    def _escapes(self, fn_node: ast.AST, handle: str,
                 spec: PairSpec) -> bool:
        """True when the handle's lifetime leaves this function: any
        Load use other than being released (returned, stored, passed
        on). Conservative — an escaped handle is someone else's
        balance to keep."""
        for n in ast.walk(fn_node):
            if not (isinstance(n, ast.Name) and n.id == handle
                    and isinstance(n.ctx, ast.Load)):
                continue
            p = getattr(n, "_ds_parent", None)
            if isinstance(p, ast.Call) and (
                    _release_target(p, spec) == handle):
                continue
            if isinstance(p, ast.Attribute) and isinstance(
                    getattr(p, "_ds_parent", None), ast.Call) \
                    and p._ds_parent.func is p \
                    and p.attr in spec.release:
                continue        # h.release()
            return True
        return False

    def _leak_path(self, cfg: CFG, call: ast.Call, handle: str,
                   spec: PairSpec) -> Optional[str]:
        """NORMAL/EXC when a path from the acquire reaches exit without
        releasing/rebinding ``handle``; None when every path settles it.
        Returns EXC when only exception paths leak."""
        start = None
        idx = 0
        for b in cfg.blocks:
            for i, s in enumerate(b.stmts):
                if any(n is call for n in shallow_walk(s)):
                    start, idx = b, i + 1
                    break
            if start is not None:
                break
        if start is None:
            return None

        def settles(stmt: ast.stmt) -> bool:
            for c in shallow_walk(stmt):
                if isinstance(c, ast.Call) \
                        and _release_target(c, spec) == handle:
                    return True
            return handle in _rebound_names(stmt)

        leak_kind: Optional[str] = None
        # DFS over (block, first-stmt-index); track whether the path so
        # far crossed an exception edge
        seen: Set[Tuple[int, int, bool]] = set()
        stack: List[Tuple[Block, int, bool]] = [(start, idx, False)]
        while stack:
            b, i, exc_path = stack.pop()
            key = (b.id, i, exc_path)
            if key in seen:
                continue
            seen.add(key)
            blocked = False
            for s in b.stmts[i:]:
                if settles(s):
                    blocked = True
                    break
            if blocked:
                continue
            if b is cfg.exit:
                if exc_path:
                    leak_kind = leak_kind or EXC
                else:
                    return NORMAL      # a plain path leaks: report that
                continue
            for succ, kind in b.succ.items():
                stack.append((succ, 0, exc_path or kind == EXC))
        return leak_kind

    # -- (b) double release ---------------------------------------------

    def _check_double_release(self, fn: FuncInfo, cfg: CFG,
                              spec: PairSpec) -> List[Finding]:
        analysis = _ReleasedNames(spec)
        in_facts, _ = solve_forward(cfg, analysis)
        out: List[Finding] = []
        for b in cfg.blocks:
            fact = in_facts.get(b, frozenset())
            for s in b.stmts:
                for call in shallow_walk(s):
                    if isinstance(call, ast.Call):
                        h = _release_target(call, spec)
                        if h and h in fact:
                            out.append(self._f(
                                fn.path, call.lineno,
                                f"`{h}` ({spec.kind}) may already be "
                                f"released when this "
                                f"`{_call_chain(call)[-1]}` runs — "
                                f"double release on some path"))
                fact = analysis.transfer_stmt(s, fact)
        return out

    # -- (c) package-wide summary balance -------------------------------

    def _check_summary_balance(self, table) -> List[Finding]:
        summaries = build_pair_summaries(table, self.pairs)
        out: List[Finding] = []
        for spec in self.pairs:
            acq_sites = [(path, name) for (path, name), s
                         in summaries.items()
                         if spec.kind in s.acquires
                         and path.startswith("deepspeed_tpu/")]
            rel_sites = [(path, name) for (path, name), s
                         in summaries.items()
                         if spec.kind in s.releases
                         and path.startswith("deepspeed_tpu/")]
            if acq_sites and not rel_sites:
                path, name = sorted(acq_sites)[0]
                fn = next(f for f in table.functions
                          if (f.path, f.name) == (path, name))
                out.append(self._f(
                    path, fn.line,
                    f"`{name}` acquires a {spec.kind} resource but "
                    f"nothing under deepspeed_tpu/ ever releases one "
                    f"({'/'.join(spec.release)}) — package-wide leak"))
        return out


# ==========================================================================
# DS017 — traced-value escape
# ==========================================================================

_HOST_SYNC_CALLS = {"float", "int", "bool"}
_HOST_SYNC_CHAINS = (["np", "asarray"], ["numpy", "asarray"],
                     ["jax", "device_get"], ["onp", "asarray"])


class _Taint(GenKill):
    """Forward taint over local names: a name is tainted when its value
    derives from a traced jit argument by data flow (metadata reads —
    .shape/.dtype/len()/isinstance() — launder the taint: they are
    static under trace)."""

    def __init__(self, sources: Set[str]):
        self.sources = sources

    def boundary(self):
        return frozenset(self.sources)

    # .. expression taint ..............................................

    def tainted(self, expr: ast.AST, fact: FrozenSet) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in fact
        if isinstance(expr, ast.Attribute):
            if expr.attr in TracedPythonBranch._OK_ATTRS:
                return False
            return self.tainted(expr.value, fact)
        if isinstance(expr, ast.Call):
            chain = _dotted(expr.func)
            if chain and chain[-1] in TracedPythonBranch._OK_CALLS:
                return False
            if chain and (chain[-1] in _HOST_SYNC_CALLS
                          or chain in _HOST_SYNC_CHAINS
                          or chain[-1] == "item"):
                return False       # host sync RESULT is a host value
            return any(self.tainted(a, fact) for a in expr.args) \
                or any(self.tainted(k.value, fact)
                       for k in expr.keywords) \
                or (isinstance(expr.func, ast.Attribute)
                    and self.tainted(expr.func.value, fact))
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                return False       # structure test: static under trace
            return self.tainted(expr.left, fact) \
                or any(self.tainted(c, fact) for c in expr.comparators)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e, fact) for e in expr.elts)
        if isinstance(expr, ast.AST):
            return any(self.tainted(v, fact)
                       for _, v in ast.iter_fields(expr)
                       if isinstance(v, ast.AST)) \
                or any(self.tainted(e, fact)
                       for _, vs in ast.iter_fields(expr)
                       if isinstance(vs, list)
                       for e in vs if isinstance(e, ast.AST))
        return False

    # .. transfer ......................................................

    def gen(self, stmt, fact):
        out: Set[str] = set()
        if isinstance(stmt, ast.Assign) \
                and self.tainted(stmt.value, fact):
            for t in stmt.targets:
                out |= {n.id for n in ast.walk(t)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Store)}
        elif isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, ast.Name) \
                and (stmt.target.id in fact
                     or self.tainted(stmt.value, fact)):
            out.add(stmt.target.id)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)) \
                and self.tainted(stmt.iter, fact):
            out |= {n.id for n in ast.walk(stmt.target)
                    if isinstance(n, ast.Name)}
        return out

    def kill(self, stmt, fact):
        if isinstance(stmt, ast.Assign) \
                and not self.tainted(stmt.value, fact):
            killed: Set[str] = set()
            for t in stmt.targets:
                killed |= {n.id for n in ast.walk(t)
                           if isinstance(n, ast.Name)
                           and isinstance(n.ctx, ast.Store)}
            return killed - self.sources
        return ()


class TracedValueEscape(InterprocRule):
    id = "DS017"
    name = "traced-value-escape"
    autofixable = False
    rationale = ("DS004 only sees a traced parameter used DIRECTLY in a "
                 "python branch; a traced value that flows through an "
                 "assignment chain into control flow, a host call "
                 "(float/int/bool/.item()/device_get) or a dict key "
                 "fails at trace time — or silently forces a host "
                 "round-trip per call — just the same")

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        ds004 = TracedPythonBranch()
        out: List[Finding] = []
        for path, tree, lines in table.files:
            # a file with no "jit" text has no jit wrapper to find
            if not any("jit" in l for l in lines):
                continue
            for fn, statics in ds004._jitted_defs(tree):
                params = set(_fn_params(fn)) - {"self", "cls"}
                sources = params - statics
                if not sources:
                    continue
                out.extend(self._check_fn(path, fn, sources))
        return _dedupe(out)

    def _check_fn(self, path: str, fn: ast.AST,
                  sources: Set[str]) -> List[Finding]:
        cfg = build_cfg(fn)
        analysis = _Taint(sources)
        in_facts, _ = solve_forward(cfg, analysis)
        out: List[Finding] = []
        for b in cfg.blocks:
            fact = in_facts.get(b, frozenset())
            if b is cfg.entry:
                fact = fact | analysis.boundary()
            for s in b.stmts:
                out.extend(self._sinks(path, s, fact, analysis, sources))
                fact = analysis.transfer_stmt(s, fact)
        # nested defs (scan bodies): inherit the taint of captured names
        for b in cfg.blocks:
            fact = in_facts.get(b, frozenset())
            for s in b.stmts:
                if isinstance(s, FUNC_TYPES):
                    captured = (fact | frozenset(sources)) \
                        - set(_fn_params(s))
                    if captured:
                        out.extend(self._check_fn(path, s, set(captured)))
                fact = analysis.transfer_stmt(s, fact)
        return out

    def _sinks(self, path: str, stmt: ast.stmt, fact: FrozenSet,
               analysis: _Taint, sources: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        derived = fact - frozenset(sources)

        def _derived_only(expr: ast.AST) -> bool:
            """DS004 already flags DIRECT traced-param uses; DS017 owns
            the assignment-chain cases it cannot see."""
            used = _names_in(expr)
            return bool(used & derived) and not (used & sources)

        if isinstance(stmt, (ast.If, ast.While)):
            test = stmt.test
            if analysis.tainted(test, fact) and _derived_only(test):
                out.append(self._f(
                    path, stmt.lineno,
                    f"python {'if' if isinstance(stmt, ast.If) else 'while'}"
                    f" branches on a value derived from a traced argument "
                    f"(assignment chain) — under jit this fails at trace "
                    f"time; use lax.cond/where or mark the argument "
                    f"static"))
        for call in shallow_walk(stmt):
            if not isinstance(call, ast.Call):
                continue
            chain = _dotted(call.func)
            if not chain:
                continue
            is_sync = (chain[-1] in _HOST_SYNC_CALLS and len(chain) == 1) \
                or chain in _HOST_SYNC_CHAINS
            if is_sync and call.args \
                    and analysis.tainted(call.args[0], fact):
                out.append(self._f(
                    path, call.lineno,
                    f"`{'.'.join(chain)}()` forces a host sync on a "
                    f"traced value inside a jitted function — this "
                    f"fails at trace time (ConcretizationTypeError); "
                    f"keep the value on device"))
            elif chain[-1] == "item" and len(chain) >= 2 \
                    and not call.args:
                recv = call.func.value
                if analysis.tainted(recv, fact):
                    out.append(self._f(
                        path, call.lineno,
                        f"`.item()` on a traced value inside a jitted "
                        f"function — fails at trace time; keep the "
                        f"value on device"))
        for node in shallow_walk(stmt):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if k is not None and analysis.tainted(k, fact):
                        out.append(self._f(
                            path, k.lineno,
                            f"a traced value is used as a dict key — "
                            f"tracers are not stable hash keys; key on "
                            f"a static instead"))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Store) \
                    and isinstance(getattr(node, "_ds_parent", None),
                                   (ast.Assign, ast.AugAssign)) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id not in fact \
                    and analysis.tainted(node.slice, fact) \
                    and isinstance(node.slice, ast.Name):
                out.append(self._f(
                    path, node.lineno,
                    f"a traced value indexes a host container store — "
                    f"tracers are not stable hash keys; key on a "
                    f"static instead"))
        return out


# ==========================================================================
# DS018 — snapshot round-trip completeness
# ==========================================================================

class SnapshotRoundTrip(InterprocRule):
    id = "DS018"
    name = "snapshot-roundtrip-completeness"
    autofixable = False
    rationale = ("the drain/resume contract is only as complete as the "
                 "snapshot: a request field the scheduler writes but "
                 "pending_snapshot/from_snapshot don't round-trip is "
                 "silently lost on a replica death (adapter_id, seed "
                 "chains and cost footprints were each retrofitted in "
                 "separate PRs) — every field must round-trip or be "
                 "declared ephemeral in SNAPSHOT_EPHEMERAL")

    ALLOWLIST_NAME = "SNAPSHOT_EPHEMERAL"

    def check_package(self, table, docs_root=None, schema_path=None,
                      partial=False):
        # cheap pre-filter off the symbol table: a module without BOTH
        # halves of the round trip has no contract to check
        snap_paths = {f.path for f in table.functions
                      if f.name == "snapshot_entry"}
        restore_paths = {f.path for f in table.functions
                         if f.name == "from_snapshot"}
        out: List[Finding] = []
        for path, tree, lines in table.files:
            if path in snap_paths and path in restore_paths:
                out.extend(self._check_module(path, tree, partial))
        return _dedupe(out)

    def _check_module(self, path: str, tree: ast.AST,
                      partial: bool) -> List[Finding]:
        snap_fn = None
        cls = None
        restore_fn = None
        for node in ast.walk(tree):
            if isinstance(node, FUNC_TYPES) \
                    and node.name == "snapshot_entry":
                snap_fn = node
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, FUNC_TYPES) \
                            and item.name == "from_snapshot":
                        cls, restore_fn = node, item
        if snap_fn is None or cls is None:
            return []

        fields = self._dataclass_fields(cls)
        if not fields:
            return []
        snap_keys = self._string_keys(snap_fn)
        restored = self._restored_kwargs(restore_fn)
        ephemeral, eph_line = self._allowlist(tree)

        out: List[Finding] = []
        for name, line in fields:
            if name in ephemeral:
                continue
            if name not in snap_keys:
                out.append(self._f(
                    path, line,
                    f"request field `{name}` is never serialized by "
                    f"snapshot_entry — a drained request silently loses "
                    f"it; add it to the snapshot or declare it in "
                    f"{self.ALLOWLIST_NAME} with a reason"))
            elif name not in restored:
                out.append(self._f(
                    path, line,
                    f"request field `{name}` is serialized by "
                    f"snapshot_entry but never restored by "
                    f"from_snapshot — the round trip drops it; restore "
                    f"it or declare it in {self.ALLOWLIST_NAME}"))
        if not partial:
            field_names = {n for n, _ in fields}
            for name in sorted(ephemeral - field_names):
                out.append(self._f(
                    path, eph_line,
                    f"{self.ALLOWLIST_NAME} declares `{name}` which is "
                    f"not a field of `{cls.name}` — stale allowlist "
                    f"entry"))
        return out

    @staticmethod
    def _dataclass_fields(cls: ast.ClassDef) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        for item in cls.body:
            if isinstance(item, ast.AnnAssign) \
                    and isinstance(item.target, ast.Name):
                out.append((item.target.id, item.lineno))
        return out

    @staticmethod
    def _string_keys(fn: ast.AST) -> Set[str]:
        """String keys the snapshot writer emits: dict-literal keys plus
        ``entry["k"] = ...`` stores."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        out.add(k.value)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Store) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                out.add(node.slice.value)
        return out

    @staticmethod
    def _restored_kwargs(fn: ast.AST) -> Set[str]:
        """Constructor keywords from_snapshot fills FROM THE ENTRY
        (``n=1`` counts as pinned, not restored)."""
        params = _fn_params(fn)
        entry_name = params[1] if len(params) > 1 else "entry"
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in ("cls",)):
                continue
            for kw in node.keywords:
                if kw.arg and entry_name in _names_in(kw.value):
                    out.add(kw.arg)
        return out

    def _allowlist(self, tree: ast.AST) -> Tuple[Set[str], int]:
        for node in getattr(tree, "body", []):
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if self.ALLOWLIST_NAME in names:
                    vals: Set[str] = set()
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) \
                                and isinstance(c.value, str):
                            vals.add(c.value)
                    return vals, node.lineno
        return set(), 0


# ==========================================================================

def dataflow_rules() -> List[InterprocRule]:
    return [JitTwinDrift(), ResourcePairing(), TracedValueEscape(),
            SnapshotRoundTrip()]


def dataflow_catalog() -> List[Dict[str, str]]:
    return [{"id": r.id, "name": r.name,
             "autofixable": r.autofixable, "rationale": r.rationale}
            for r in dataflow_rules()]
