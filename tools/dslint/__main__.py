"""``python -m tools.dslint [paths...]`` — run the JAX-aware lint.

Exit code 0 when every finding is fixed, suppressed inline, or in the
baseline; 1 otherwise. ``--update-baseline`` rewrites the checked-in
baseline from the current tree (visible debt, non-blocking).

Two phases: the per-file rules (DS001–DS010) and the package-wide
rules over a shared symbol table — interprocedural (DS011–DS014) and
flow-sensitive dataflow (DS015–DS018). ``--closure`` switches to quick
mode: the positional paths are treated as *changed files* and the lint
runs over them plus their direct importers (from the cached import
graph), with the whole-tree completeness checks disabled; the cache
key includes the content hashes of jit_registry.py and
telemetry_schema.json, so editing either forces a full re-analysis.
``--sarif PATH`` additionally writes a SARIF 2.1.0 log.
``--explain DS0NN`` prints one rule's doc + a minimal true positive.
"""

import argparse
import sys

from tools.dslint.core import (DEFAULT_BASELINE, analyze_package,
                               apply_baseline, findings_to_json,
                               load_baseline, write_baseline)
from tools.dslint.interproc import interproc_catalog, interproc_rules
from tools.dslint.rules import default_rules, rule_catalog


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dslint",
        description="JAX/TPU-aware static analysis (rules DS001-DS018; "
                    "see docs/LINT.md)")
    ap.add_argument("paths", nargs="*", default=["deepspeed_tpu", "tools"],
                    help="files or directories (default: deepspeed_tpu "
                         "tools); with --closure: the changed files")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: tools/dslint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as failures too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="DS0NN", default=None,
                    help="print one rule's doc + a minimal true-positive "
                         "example, then exit")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print baselined findings in text mode")
    ap.add_argument("--sarif", metavar="PATH", default=None,
                    help="also write a SARIF 2.1.0 log to PATH")
    ap.add_argument("--stats", action="store_true",
                    help="print per-phase timing to stderr")
    ap.add_argument("--closure", action="store_true",
                    help="quick mode: lint the given changed files plus "
                         "their direct importers (cached import graph); "
                         "whole-tree completeness checks are skipped")
    args = ap.parse_args(argv)

    if args.explain:
        from tools.dslint.explain import explain
        text = explain(args.explain)
        if text is None:
            print(f"no such rule: {args.explain}", file=sys.stderr)
            return 2
        print(text)
        return 0

    if args.list_rules:
        for r in rule_catalog() + interproc_catalog():
            fix = " [autofixable]" if r["autofixable"] else ""
            print(f"{r['id']} {r['name']}{fix}\n    {r['rationale']}")
        return 0

    rules = default_rules()
    inter = interproc_rules()
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in wanted]
        inter = [r for r in inter if r.id in wanted]
        if not rules and not inter:
            print(f"no such rules: {args.rules}", file=sys.stderr)
            return 2

    paths = args.paths or ["deepspeed_tpu", "tools"]
    if args.closure:
        from tools.dslint.symbols import closure_of, load_callgraph_cache
        from tools.dslint.core import REPO_ROOT, _norm_path
        changed = [_norm_path(p) for p in paths if p.endswith(".py")]
        imports = load_callgraph_cache()
        if not imports:
            # no cache yet (first run): fall back to a full-tree pass,
            # which also writes the cache for next time
            args.closure = False
            paths = ["deepspeed_tpu", "tools", "tests"]
        else:
            paths = [str(REPO_ROOT / p)
                     for p in closure_of(changed, imports)]
            if not paths:
                print("dslint: no python files in closure")
                return 0

    # the completeness directions ("declared but never fired", "in the
    # schema but registered by no code") only hold over the whole tree:
    # run them when the package root is in scope, not on a targeted
    # file/subdir lint (where absence just means "not analyzed")
    from pathlib import Path as _P
    from tools.dslint.core import REPO_ROOT as _ROOT
    pkg_root = (_ROOT / "deepspeed_tpu").resolve()
    partial = args.closure or not any(
        _P(p).resolve() == pkg_root for p in paths)

    stats = {}
    symtab_out = []
    findings = analyze_package(
        paths, rules=rules, interproc=inter, partial=partial,
        stats=stats, symtab_out=symtab_out)

    if not partial and symtab_out:
        # full-tree pass: refresh the import-graph cache quick mode uses
        from tools.dslint.symbols import write_callgraph_cache
        try:
            write_callgraph_cache(symtab_out[0])
        except OSError:
            pass

    if args.stats:
        print("dslint: {files:.0f} files, parse {parse_s:.2f}s, "
              "intraproc {intraproc_s:.2f}s, interproc {interproc_s:.2f}s,"
              " total {total_s:.2f}s".format(**stats), file=sys.stderr)

    if args.update_baseline:
        out = write_baseline(findings, args.baseline)
        print(f"dslint: baseline written to {out} "
              f"({len(findings)} entries)")
        return 0

    baseline = load_baseline(args.baseline) if not args.no_baseline else {}
    new, baselined = apply_baseline(findings, baseline)

    if args.sarif:
        from tools.dslint.sarif import write_sarif
        write_sarif(args.sarif, new, baselined,
                    rules=rule_catalog() + interproc_catalog())

    if args.format == "json":
        print(findings_to_json(new, baselined))
    else:
        for f in new:
            print(f.format())
            if f.snippet:
                print(f"    {f.snippet}")
        if args.show_baselined:
            for f in baselined:
                print(f.format())
        n_files = len({f.path for f in new})
        print(f"dslint: {len(new)} finding(s) in {n_files} file(s), "
              f"{len(baselined)} baselined")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
