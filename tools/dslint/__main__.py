"""``python -m tools.dslint [paths...]`` — run the JAX-aware lint.

Exit code 0 when every finding is fixed, suppressed inline, or in the
baseline; 1 otherwise. ``--update-baseline`` rewrites the checked-in
baseline from the current tree (visible debt, non-blocking).
"""

import argparse
import sys

from tools.dslint.core import (DEFAULT_BASELINE, analyze_paths,
                               apply_baseline, findings_to_json,
                               load_baseline, write_baseline)
from tools.dslint.rules import default_rules, rule_catalog


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dslint",
        description="JAX/TPU-aware static analysis (rules DS001-DS008; "
                    "see docs/LINT.md)")
    ap.add_argument("paths", nargs="*", default=["deepspeed_tpu", "tools"],
                    help="files or directories (default: deepspeed_tpu "
                         "tools)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: tools/dslint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as failures too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print baselined findings in text mode")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in rule_catalog():
            fix = " [autofixable]" if r["autofixable"] else ""
            print(f"{r['id']} {r['name']}{fix}\n    {r['rationale']}")
        return 0

    rules = default_rules()
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in wanted]
        if not rules:
            print(f"no such rules: {args.rules}", file=sys.stderr)
            return 2

    paths = args.paths or ["deepspeed_tpu", "tools"]
    findings = analyze_paths(paths, rules=rules)

    if args.update_baseline:
        out = write_baseline(findings, args.baseline)
        print(f"dslint: baseline written to {out} "
              f"({len(findings)} entries)")
        return 0

    baseline = load_baseline(args.baseline) if not args.no_baseline else {}
    new, baselined = apply_baseline(findings, baseline)

    if args.format == "json":
        print(findings_to_json(new, baselined))
    else:
        for f in new:
            print(f.format())
            if f.snippet:
                print(f"    {f.snippet}")
        if args.show_baselined:
            for f in baselined:
                print(f.format())
        n_files = len({f.path for f in new})
        print(f"dslint: {len(new)} finding(s) in {n_files} file(s), "
              f"{len(baselined)} baselined")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
