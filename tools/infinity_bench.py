"""Throughput bench for the ZeRO-Infinity streamed tier (gpt2-4b / 8b).

VERDICT r4 #3: the 4B/8B regression configs
(ref: tests/model/Megatron_GPT2/run_perf_baseline.py:33,48 — 64L/2304h
and 72L/3072h on 16 GPUs; ref capacity claim "13B on one 32GB V100 at
>30 TFLOPS", docs/_pages/features.md:116) have only ever been run here
as a CAPACITY demo. This tool measures the streamed tier for SPEED:

- measured host<->device link bandwidths (h2d via device_put of a
  pinned block, d2h via copy_to_host of a device buffer) — on the
  tunnel rig these are the honest caveat (PERF.md measured d2h
  0.022 GB/s, ~3 orders below a real TPU-VM PCIe link);
- per-step wall time -> tokens/s + MFU (Megatron flops accounting);
- the analytic transfer floor for the measured link: bytes streamed
  per step (2x block h2d + 1x grads d2h per micro-batch) / bandwidth —
  so the report separates "engine overhead" from "link physics":
  overlap_quality = transfer_floor / step_time (→1.0 means the step is
  fully transfer-bound with compute hidden behind DMA, the best any
  schedule can do on this link; small values mean the engine, not the
  link, is the bottleneck).

Prints one JSON line per phase; chip_queue item "infinity".

Usage: python tools/infinity_bench.py [preset] [steps] [micro_batch] [seq]
"""

import json
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request  # noqa: E402

honor_platform_request()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def measure_bandwidths(mb=256):
    """Measured h2d / d2h GB/s with a mb-MB fp32 buffer (median of 3)."""
    n = mb * (1 << 20) // 4
    host = np.ones(n, np.float32)
    h2d = []
    for _ in range(3):
        t0 = time.perf_counter()
        d = jax.block_until_ready(jax.device_put(host))
        h2d.append(time.perf_counter() - t0)
    d2h = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(d)
        d2h.append(time.perf_counter() - t0)
    gb = host.nbytes / 1e9
    return gb / sorted(h2d)[1], gb / sorted(d2h)[1]


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2-4b"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else 1024

    h2d_gbs, d2h_gbs = measure_bandwidths()
    print(json.dumps({"phase": "link", "h2d_gb_s": round(h2d_gbs, 3),
                      "d2h_gb_s": round(d2h_gbs, 4)}), flush=True)

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    cfg = gpt.preset(preset, max_seq_len=seq, dtype=jnp.bfloat16,
                     remat=True, use_flash_attention=on_tpu,
                     flash_block_q=512, flash_block_kv=512)
    fac = gpt.host_param_factory(0, cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=fac,
        config={
            "train_batch_size": batch,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3,
                                  "offload_param": {"device": "cpu"}},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        })

    # streamed bytes per optimizer step (see module docstring):
    # h2d 2x bf16 block per micro-batch, d2h 1x bf16 grads per micro-batch
    block_bytes = sum(sum(a.nbytes for a in grp) for grp in eng.host_bf16)
    gas = eng.gas
    h2d_bytes = 2 * block_bytes * gas
    d2h_bytes = block_bytes * gas
    floor_s = h2d_bytes / 1e9 / h2d_gbs + d2h_bytes / 1e9 / d2h_gbs

    r = np.random.default_rng(0)
    data = {"tokens": r.integers(0, cfg.vocab_size,
                                 (batch, seq + 1)).astype(np.int32)}
    m = eng.train_batch(data)                       # warmup / compile
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        m = eng.train_batch(data)
        times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[len(times) // 2]
    tps = batch * seq / dt
    flops = gpt.train_flops_per_token(cfg, seq)
    from bench import peak_flops
    mfu = tps * flops / peak_flops()
    print(json.dumps({
        "phase": "train", "metric": f"{preset}_streamed_tokens_per_s",
        "value": round(tps, 2), "unit": "tokens/s/chip",
        "model": preset, "n_params": eng.n_params, "batch": batch,
        "seq": seq, "step_s": round(dt, 2), "mfu": round(mfu, 5),
        "loss": round(m["loss"], 4),
        "streamed_gb_per_step": round((h2d_bytes + d2h_bytes) / 1e9, 2),
        "transfer_floor_s": round(floor_s, 2),
        "overlap_quality": round(min(1.0, floor_s / dt), 4),
        "caveat": ("tunnel-rig link: d2h measured ~0.02 GB/s — the floor "
                   "is link physics, not engine scheduling; see PERF.md"
                   if d2h_gbs < 0.5 else None)}), flush=True)


if __name__ == "__main__":
    main()
