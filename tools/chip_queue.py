"""Serialized on-chip measurement queue.

The rig exposes ONE real TPU through a tunnel whose remote compile helper
wedges under concurrent use and borderline-HBM compiles (see PERF.md).
This driver runs each measurement in its own subprocess, STRICTLY one at
a time, with a health probe between items — fire it once and collect
every number needed for PERF.md/BENCH in a single pass.

Usage: python tools/chip_queue.py [item ...]
Items default to the full queue; each prints its JSON line(s) as it lands.
"""

import json
import os
import subprocess
import sys
import time

HEALTH = (
    # honor an explicit JAX_PLATFORMS request (the recovery REHEARSAL
    # probes the CPU backend); with no request this probes the real
    # accelerator exactly as before
    "import sys; sys.path.insert(0, '.')\n"
    "from deepspeed_tpu.utils import honor_platform_request\n"
    "honor_platform_request()\n"
    "import jax, jax.numpy as jnp\n"
    "print('devices', jax.devices())\n"
    "print('ok', float(jax.jit(lambda a: (a@a).sum())"
    "(jnp.ones((256,256), jnp.bfloat16))))\n"
)

QUEUE = [
    # THE ROUND'S DELIVERABLE FIRST: the headline probe variants use only
    # the plain flash path already proven through real Mosaic (round-2
    # headline + this round's 'plain'/'gqa' smoke passes) — the feature-
    # matrix smoke runs AFTER the measurement is in the bank. Variants
    # pass the analytic memory guard inside headline_probe — unsafe
    # configs (the rig-wedging borderline-HBM compiles) are skipped with
    # a JSON line, never attempted.
    # outer budget covers 14 variants x the probe's 2400s per-config cap;
    # ordering is greedy: baseline re-confirmation, then the single
    # biggest lever (offload_flash), then its combinations, then tiles
    ("probe", [sys.executable, "tools/headline_probe.py",
               "b16-full-ce", "b16-offloadflash-ce",
               "b16-offloadflash-bwd512", "b18-offloadflash-ce",
               "b20-offloadflash-ce", "b20-full-ce",
               "b22-full-ce", "b12-flashonly-ce", "b12-flashonly-bwd512",
               "b16-bwd512", "b16-bwdq512", "b16-bwdkv512",
               "med-b8-noremat", "med-b16-ce"], 33700),
    ("trace-1.5b", [sys.executable, "tools/trace_analyze.py", "run",
                    "gpt2-1.5b", "16", "full", "2048"], 1500),
    # compile/parity-check the flash kernel feature matrix through the
    # REAL Mosaic lowering — WITHOUT the sliding-window cases: the r4
    # 'window' compile hung the remote compile helper and wedged the rig
    # for ~20min (chipq_phase1 log); window cases are quarantined in
    # their own LAST item so a repeat costs nothing but itself
    ("flash-smoke", [sys.executable, "tools/flash_chip_smoke.py",
                     "plain", "kv_mask", "segments", "gqa", "bwd-tiles",
                     "ring-blocks"], 1800),
    # outer budgets cover each tool's own per-config 1500s timeouts
    ("bert-grid", [sys.executable, "tools/bert_bench.py", "8"], 9200),
    ("moe", [sys.executable, "tools/moe_bench.py", "8"], 6200),
    ("longcontext", [sys.executable, "tools/longcontext_bench.py", "chip"],
     4800),
    ("infer", [sys.executable, "tools/infer_bench.py"], 3600),
    # unattended autotune over the headline family (guard-pruned,
    # subprocess-isolated experiments; prints probe-format lines so
    # pick_headline weighs them with the same margin logic)
    ("autotune", [sys.executable, "tools/autotune_headline.py",
                  "--trials", "8", "--timeout", "1500"], 13500),
    # streamed-tier THROUGHPUT (VERDICT r4 #3): 4B first, then the
    # offloaded 8B; link bandwidths + transfer floor recorded with the
    # tunnel caveat
    ("infinity-4b", [sys.executable, "tools/infinity_bench.py",
                     "gpt2-4b", "3", "4", "1024"], 3600),
    ("infinity-8b", [sys.executable, "tools/infinity_bench.py",
                     "gpt2-8b", "2", "2", "1024"], 4800),
    # the quarantined window compiles, dead last: FIRST the bisect
    # (minimized kernels, one construct per subprocess — classifies the
    # r4 hang instead of reproducing it), then the full smoke cases
    ("window-bisect", [sys.executable, "tools/flash_window_bisect.py"],
     7600),
    ("flash-smoke-window", [sys.executable, "tools/flash_chip_smoke.py",
                            "window", "window+gqa+segs",
                            "ring-blocks-window"], 1800),
    # CPU-backend rehearsal of the recovery cycle (refuses to run
    # without DS_REHEARSAL=1, never on a TPU backend) — exercised by
    # tests/test_rig_recovery.py, never part of the default queue
    ("probe-rehearsal", [sys.executable, "tools/rehearse_probe.py"], 900),
]
# default drain excludes rehearsal-only items
DEFAULT_ITEMS = [q[0] for q in QUEUE if q[0] != "probe-rehearsal"]


def healthy(timeout=180):
    # fault injection for the recovery-rehearsal down-path test; shout
    # so a lingering env var can never masquerade as a dead rig
    if os.environ.get("DS_CHIP_FORCE_DOWN"):
        print(json.dumps({"probe": "DS_CHIP_FORCE_DOWN override active — "
                                   "reporting down WITHOUT probing"}),
              flush=True)
        return False
    try:
        r = subprocess.run([sys.executable, "-c", HEALTH],
                           capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    wanted = sys.argv[1:] or DEFAULT_ITEMS
    items = [q for q in QUEUE if q[0] in wanted]
    for name, cmd, tmo in items:
        # retry the probe a few times before giving an item up — a
        # transient tunnel wedge must not drop a whole measurement set
        for attempt in range(4):
            if healthy():
                break
            print(json.dumps({"item": name, "unhealthy_attempt": attempt}),
                  flush=True)
            if attempt < 3:          # no point sleeping after the last probe
                time.sleep(120)
        else:
            print(json.dumps({"item": name, "skipped": "chip unhealthy"}),
                  flush=True)
            continue
        t0 = time.time()
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=tmo)
            print(f"== {name} (rc={r.returncode}, "
                  f"{round(time.time()-t0)}s) ==", flush=True)
            print(r.stdout.strip()[-4000:], flush=True)
            if r.returncode != 0:
                print("stderr:", r.stderr.strip()[-600:], flush=True)
        except subprocess.TimeoutExpired as e:
            # keep whatever JSON lines already landed before the hang
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode("utf-8", "replace")
            print(json.dumps({"item": name, "timeout_s": tmo}), flush=True)
            if partial.strip():
                print(f"partial output before timeout:\n"
                      f"{partial.strip()[-2000:]}", flush=True)


if __name__ == "__main__":
    main()
