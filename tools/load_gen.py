"""Deterministic serving load generator: seeded request mixes +
arrival processes + a drive loop that records per-request timestamps.

The closed observability loop (docs/OBSERVABILITY.md) needs load that
is (a) shaped like real traffic — bursty arrivals, heterogeneous
prompt/output lengths, priority classes — and (b) exactly replayable,
so an autoscale decision timeline can be compared run-over-run and a
bench row regressed bit-for-bit. This module provides both halves:

- **mixes** (``MIXES``): named request populations — ``chat`` (short
  shared-system-prompt turns, interactive-heavy), ``rag`` (long-prefill
  retrieval contexts, short answers), ``repetitive`` (tiny-alphabet
  highly-predictable prompts, the spec-decode-friendly shape, batch-
  heavy), ``heavy_tail`` (adversarial Pareto-tailed lengths) and
  ``multitenant`` (a Zipf-popular LoRA tenant population plus a
  base-only fraction — the adapter-pool / adapter-affinity shape,
  docs/ADAPTERS.md) and ``mixed`` (the rag and chat populations
  interleaved, rag prefixes Zipf-popular, per-kind SLO budgets in
  :data:`SLO_TARGETS` — the disaggregated prefill/decode workload,
  docs/ROBUSTNESS.md);
- **arrivals**: an open-loop Poisson process over piecewise-constant
  rate ``phases`` (``[(duration, rate), ...]`` — a spike is just a
  high-rate middle phase), or a burst (every request at t=0) for
  closed-loop driving;
- **trace save/replay**: :func:`save_trace` / :func:`load_trace`
  round-trip the generated request list through JSON, so a run can be
  replayed against a different fleet shape with identical input;
- **drive loop** (:func:`drive`): submits against anything with the
  ``submit(req, now)`` / ``step(now)`` / ``busy`` surface (a
  ``ServingEngine`` or a ``ReplicaRouter``), open- or closed-loop, and
  returns per-request ``submitted/first_token/finished`` timestamps
  plus SLO attainment — the offline-recomputable record the bench rows
  embed.

Everything is a pure function of the explicit ``seed`` (no ambient
randomness — the dslint DS010 contract extended to the harness): same
seed, same mix, same phases => byte-identical request list and, against
a deterministic fleet, an identical decision timeline.

CLI: ``python -m tools.load_gen --seed 0 --mix chat
--phases 20:0.5,10:2,20:0.5 --out trace.json`` writes a replayable
trace; add ``--summary`` to print the population digest.
"""

import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# terminal request states the drive loop treats as "finished"
_TERMINAL = ("done", "timeout", "shed", "error")

# mix parameters: prompt/output length ranges are inclusive uniform
# unless pareto=True (heavy tail: lo + Pareto(alpha) * scale, clipped);
# shared_prefix tokens are common to every request in the population
# (the prefix-cache / affinity-routing shape); alphabet restricts token
# ids to a tiny range (highly predictable text, the speculative-decode
# friendly regime); batch_frac is the probability a request carries
# priority="batch" instead of "interactive"
MIXES: Dict[str, Dict[str, Any]] = {
    "chat": dict(plen=(4, 12), new=(4, 16), shared_prefix=4,
                 alphabet=None, batch_frac=0.1, pareto=False),
    "rag": dict(plen=(20, 40), new=(2, 8), shared_prefix=12,
                alphabet=None, batch_frac=0.5, pareto=False),
    "repetitive": dict(plen=(8, 24), new=(8, 24), shared_prefix=0,
                       alphabet=8, batch_frac=0.7, pareto=False),
    "heavy_tail": dict(plen=(3, 40), new=(2, 24), shared_prefix=0,
                       alphabet=None, batch_frac=0.5, pareto=True),
    # adapters: tenant population size; zipf_a: popularity skew (a few
    # hot tenants, a long warm tail — the pool-hit/eviction shape);
    # base_frac: requests that name no adapter at all. shared_prefix
    # stays 0: adapter requests bypass prefix sharing by design
    "multitenant": dict(plen=(6, 16), new=(4, 12), shared_prefix=0,
                        alphabet=None, batch_frac=0.2, pareto=False,
                        adapters=6, zipf_a=1.5, base_frac=0.25),
    # mixed (the disaggregation workload, docs/ROBUSTNESS.md): the rag
    # and chat populations interleaved — long batch-heavy prefills
    # fighting short interactive decodes for the same slots is exactly
    # the contention the prefill/decode split resolves. Each request
    # keeps its component's kind/priority/SLO budget; rag requests
    # draw their document prefix from a Zipf-popular family (a few hot
    # contexts, a long warm tail). Composite: per-request parameters
    # come from the named component mixes.
    # Overrides reshape the components for disaggregation stress: rag
    # prompts grow to real document length (40-64 tokens, 5-8 prefill
    # chunks — the head-of-line block a mixed fleet suffers) and its
    # answers become grounded spans rather than 2-token acks (a
    # 2-token stream's "mean inter-token gap" is ONE gap, so TPOT
    # would be meaningless); chat answers lengthen so its decode
    # stream is long enough for inter-token stalls to register.
    "mixed": dict(components=("chat", "rag"), rag_frac=0.6,
                  prefix_families=4, zipf_a=1.4,
                  overrides={"rag": {"plen": (40, 64), "new": (4, 8)},
                             "chat": {"new": (6, 16)}}),
}

# per-kind SLO budgets in scheduler token-time units (one unit ≈ one
# decode iteration): ``ttft`` bounds submit -> first token, ``tpot``
# bounds the mean inter-token gap of the decode stream. These are the
# targets the disagg compare row must hold for BOTH kinds at once
# (tools/infer_bench.py bench_serving_disagg_compare); drive() records
# the raw per-request numbers so attainment is offline-recomputable.
SLO_TARGETS: Dict[str, Dict[str, float]] = {
    "chat": {"ttft": 12.0, "tpot": 2.5},
    "rag": {"ttft": 14.0, "tpot": 8.0},
    "repetitive": {"ttft": 16.0, "tpot": 3.0},
    "heavy_tail": {"ttft": 30.0, "tpot": 4.0},
    "multitenant": {"ttft": 16.0, "tpot": 3.0},
}

TRACE_VERSION = 1


def poisson_arrivals(phases: Sequence[Tuple[float, float]],
                     seed: int) -> List[float]:
    """Arrival instants of a Poisson process with piecewise-constant
    rate: for each ``(duration, rate)`` phase, exponential inter-
    arrival gaps at that rate until the phase's time is spent. Rate 0
    phases contribute silence. Deterministic in ``seed``."""
    rng = np.random.default_rng(seed)
    out: List[float] = []
    t0 = 0.0
    for duration, rate in phases:
        duration = float(duration)
        if rate > 0:
            t = t0 + float(rng.exponential(1.0 / rate))
            while t < t0 + duration:
                out.append(t)
                t += float(rng.exponential(1.0 / rate))
        t0 += duration
    return out


def make_requests(*, seed: int, mix: str = "chat", n: Optional[int] = None,
                  phases: Optional[Sequence[Tuple[float, float]]] = None,
                  vocab_size: int = 128,
                  max_prompt_len: int = 48) -> List[Dict]:
    """Generate a deterministic request population. With ``phases`` the
    arrival instants come from the Poisson process (``n`` then caps the
    count if given); without, ``n`` requests all arrive at t=0 (a burst
    — the closed-loop shape). Each entry is JSON-plain:
    ``{rid, at, kind, priority, prompt, max_new_tokens}``."""
    if mix not in MIXES:
        raise ValueError(f"unknown mix {mix!r}; have {sorted(MIXES)}")
    if phases is None and n is None:
        raise ValueError("need n= (burst) or phases= (poisson)")
    params = MIXES[mix]
    if phases is not None:
        ats = poisson_arrivals(phases, seed)
        if n is not None:
            ats = ats[:n]
    else:
        ats = [0.0] * int(n)
    rng = np.random.default_rng(seed + 1)     # independent of arrivals
    if "components" in params:
        return _composite_requests(mix, params, ats, rng,
                                   vocab_size=vocab_size,
                                   max_prompt_len=max_prompt_len)
    lo_tok, hi_tok = 1, vocab_size            # 0 reserved (pad/eos)
    if params["alphabet"]:
        hi_tok = min(vocab_size, lo_tok + params["alphabet"])
    shared = rng.integers(
        lo_tok, hi_tok, params["shared_prefix"]).tolist() \
        if params["shared_prefix"] else []

    def length(lo: int, hi: int) -> int:
        if params["pareto"]:
            v = lo + rng.pareto(1.5) * (hi - lo) / 4.0
            return int(min(max(v, lo), hi))
        return int(rng.integers(lo, hi + 1))

    def adapter() -> Optional[str]:
        n_adapters = params.get("adapters")
        if not n_adapters or rng.random() < params.get("base_frac", 0.0):
            return None
        # Zipf draw folded onto the tenant population: tenant-0 is the
        # hot adapter, the tail stays warm (the LRU-pool shape)
        return f"tenant-{(int(rng.zipf(params['zipf_a'])) - 1) % n_adapters}"

    out: List[Dict] = []
    for i, at in enumerate(ats):
        plen = min(length(*params["plen"]), max_prompt_len)
        tail = max(1, plen - len(shared))
        prompt = shared + rng.integers(lo_tok, hi_tok, tail).tolist()
        out.append({
            "rid": f"{mix}-{i}",
            "at": float(at),
            "kind": mix,
            "priority": ("batch" if rng.random() < params["batch_frac"]
                         else "interactive"),
            "adapter_id": adapter(),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": length(*params["new"]),
        })
    return out


def _composite_requests(mix: str, params: Dict, ats: List[float],
                        rng: np.random.Generator, *, vocab_size: int,
                        max_prompt_len: int) -> List[Dict]:
    """Composite-mix population (``components`` in MIXES): each request
    draws its component by ``rag_frac`` and keeps that component's
    ``kind`` (so per-kind SLO budgets in :data:`SLO_TARGETS` apply
    per request). Chat requests share one system prefix; rag requests
    pick their document prefix from a Zipf-popular family. Pure in the
    passed ``rng`` — same seed, byte-identical trace."""
    comp = {name: dict(MIXES[name], **params.get("overrides", {})
                       .get(name, {}))
            for name in params["components"]}
    lo_tok = 1
    chat_shared = rng.integers(
        lo_tok, vocab_size, comp["chat"]["shared_prefix"]).tolist()
    families = [rng.integers(lo_tok, vocab_size,
                             comp["rag"]["shared_prefix"]).tolist()
                for _ in range(int(params["prefix_families"]))]
    out: List[Dict] = []
    for i, at in enumerate(ats):
        kind = "rag" if rng.random() < params["rag_frac"] else "chat"
        p = comp[kind]
        plen = min(int(rng.integers(p["plen"][0], p["plen"][1] + 1)),
                   max_prompt_len)
        if kind == "rag":
            fam = (int(rng.zipf(params["zipf_a"])) - 1) % len(families)
            shared = families[fam]
        else:
            shared = chat_shared
        tail = max(1, plen - len(shared))
        prompt = shared + rng.integers(lo_tok, vocab_size, tail).tolist()
        out.append({
            "rid": f"{mix}-{i}",
            "at": float(at),
            "kind": kind,
            "priority": ("batch" if rng.random() < p["batch_frac"]
                         else "interactive"),
            "adapter_id": None,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(rng.integers(p["new"][0],
                                               p["new"][1] + 1)),
        })
    return out


def save_trace(path: str, requests: List[Dict], *, seed: int,
               mix: str = "", meta: Optional[Dict] = None) -> str:
    """Persist a request population as a replayable JSON trace."""
    body = {"version": TRACE_VERSION, "seed": seed, "mix": mix,
            "meta": meta or {}, "requests": requests}
    with open(path, "w") as f:
        json.dump(body, f)
    return path


def load_trace(path: str) -> List[Dict]:
    """Load a trace written by :func:`save_trace`; returns the request
    list (arrival order preserved)."""
    with open(path) as f:
        body = json.load(f)
    if body.get("version") != TRACE_VERSION:
        raise ValueError(
            f"{path}: trace version {body.get('version')!r}, "
            f"expected {TRACE_VERSION}")
    return body["requests"]


def _mk_serve_requests(entries: List[Dict]) -> List:
    from deepspeed_tpu.inference.serving import ServeRequest
    return [ServeRequest(rid=e["rid"],
                         prompt=np.asarray(e["prompt"], np.int32),
                         max_new_tokens=int(e["max_new_tokens"]),
                         priority=e.get("priority"),
                         adapter_id=e.get("adapter_id"))
            for e in entries]


def drive(target, entries: List[Dict], *, mode: str = "open",
          concurrency: int = 8, slo_ttft: Optional[float] = None,
          max_steps: int = 100_000, include_tokens: bool = False) -> Dict:
    """Run a generated population against ``target`` (ServingEngine or
    ReplicaRouter — anything with ``submit(req, now)`` / ``step(now)``
    / ``busy``), stepping the scheduler clock in token-time units —
    one unit per iteration at N=1, up to N units when a fused decode
    horizon (``DS_DECODE_HORIZON``) emits several tokens per step.

    - ``mode="open"``: requests are submitted when the clock reaches
      their ``at`` — queueing delay under a spike is real (the
      fixed-fleet SLO-violation shape the autoscale bench contrasts).
    - ``mode="closed"``: arrival times are ignored; at most
      ``concurrency`` requests are outstanding, the next one submitted
      as soon as one finishes (throughput-probe shape).

    Returns ``{"per_request": [...], "steps", "slo_attainment",
    "ttft_p50/p95/p99", "tpot_p50/p95/p99"}`` where each per-request
    record carries ``submitted_at`` / ``first_token_at`` /
    ``finished_at`` / ``state`` / ``ttft`` / ``tpot`` — the offline-
    recomputable SLO record (``tpot`` is the mean inter-token gap of
    the decode stream, None for < 2 generated tokens).
    ``slo_attainment`` (when ``slo_ttft`` is given) counts a request
    attained iff it got its first token within the budget; requests
    that never produced one (shed, still queued at exhaustion) count
    as misses. ``include_tokens=True`` embeds each request's final
    ``tokens`` so two runs can assert token-identical output (the
    disagg compare row's ``output_identical`` check)."""
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be open|closed, got {mode!r}")
    if hasattr(target, "token_time_unit"):
        # the driver's clock is in token-time units (one unit ≈ one
        # decode iteration); telling the engine so makes a fused
        # horizon stamp its i-th in-horizon token at ``clock + i``
        # — the exact instants the N=1 loop would have used, keeping
        # ttft/tpot records and deadline enforcement bit-identical
        # at any DS_DECODE_HORIZON (docs/MULTISTEP.md)
        target.token_time_unit = 1.0
    order = sorted(range(len(entries)), key=lambda i: entries[i]["at"]) \
        if mode == "open" else list(range(len(entries)))
    reqs = _mk_serve_requests(entries)
    clock = 0.0
    steps = 0
    nxt = 0                                   # next request to submit
    live: List = []                           # submitted, maybe running
    while nxt < len(order) or target.busy:
        if mode == "open":
            while nxt < len(order) \
                    and entries[order[nxt]]["at"] <= clock:
                r = reqs[order[nxt]]
                target.submit(r, now=clock)
                live.append(r)
                nxt += 1
            if not target.busy and nxt < len(order):
                # idle gap before the next arrival: fast-forward the
                # clock instead of spinning empty steps
                clock = max(clock, entries[order[nxt]]["at"])
                continue
        else:
            inflight = sum(1 for r in live if r.state not in _TERMINAL)
            while nxt < len(order) and inflight < concurrency:
                r = reqs[order[nxt]]
                target.submit(r, now=clock)
                live.append(r)
                nxt += 1
                if r.state not in _TERMINAL:
                    inflight += 1
        target.step(clock)
        # a fused multi-step horizon emits up to N tokens per step;
        # advance by the tokens actually produced so the next arrivals
        # land at the same token-time they would under N=1
        clock += max(1.0, float(getattr(target, "last_step_span", 1.0)))
        steps += 1
        if steps > max_steps:
            raise RuntimeError(f"load did not drain in {max_steps} steps")

    per_request: List[Dict] = []
    ttfts: List[float] = []
    tpots: List[float] = []
    attained = 0
    for e, r in zip(entries, reqs):
        ttft = (r.first_token_at - r.submitted_at
                if r.first_token_at is not None
                and r.submitted_at is not None else None)
        if ttft is not None:
            ttfts.append(ttft)
            if slo_ttft is not None and ttft <= slo_ttft:
                attained += 1
        tpot = ((r.finished_at - r.first_token_at) / (len(r.out) - 1)
                if r.first_token_at is not None
                and r.finished_at is not None and len(r.out) > 1
                else None)
        if tpot is not None:
            tpots.append(tpot)
        rec = {
            "rid": e["rid"], "kind": e["kind"],
            "priority": e.get("priority"), "arrival": e["at"],
            "submitted_at": r.submitted_at,
            "first_token_at": r.first_token_at,
            "finished_at": r.finished_at,
            "state": r.state, "ttft": ttft, "tpot": tpot,
            "generated": len(r.out),
        }
        if include_tokens:
            rec["tokens"] = [int(t) for t in r.tokens]
        per_request.append(rec)
    arr = np.asarray(ttfts) if ttfts else np.asarray([0.0])
    tarr = np.asarray(tpots) if tpots else np.asarray([0.0])
    return {
        "per_request": per_request,
        "steps": steps,
        "requests": len(entries),
        "slo_attainment": (attained / len(entries)
                           if slo_ttft is not None and entries else None),
        "ttft_p50": float(np.percentile(arr, 50)),
        "ttft_p95": float(np.percentile(arr, 95)),
        "ttft_p99": float(np.percentile(arr, 99)),
        "tpot_p50": float(np.percentile(tarr, 50)),
        "tpot_p95": float(np.percentile(tarr, 95)),
        "tpot_p99": float(np.percentile(tarr, 99)),
    }


def _parse_phases(spec: str) -> List[Tuple[float, float]]:
    """``"20:0.5,10:2,20:0.5"`` -> [(20, 0.5), (10, 2), (20, 0.5)]."""
    out = []
    for part in spec.split(","):
        dur, rate = part.split(":")
        out.append((float(dur), float(rate)))
    return out


def main(argv: List[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="generate a replayable serving load trace")
    ap.add_argument("--seed", type=int, required=True,
                    help="explicit seed (no ambient randomness)")
    ap.add_argument("--mix", default="chat", choices=sorted(MIXES))
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--phases", default=None,
                    help="piecewise Poisson rates, e.g. 20:0.5,10:2")
    ap.add_argument("--vocab-size", type=int, default=128)
    ap.add_argument("--max-prompt-len", type=int, default=48)
    ap.add_argument("--out", default=None, help="trace JSON path")
    ap.add_argument("--summary", action="store_true")
    args = ap.parse_args(argv)
    reqs = make_requests(
        seed=args.seed, mix=args.mix, n=args.n,
        phases=_parse_phases(args.phases) if args.phases else None,
        vocab_size=args.vocab_size, max_prompt_len=args.max_prompt_len)
    if args.out:
        save_trace(args.out, reqs, seed=args.seed, mix=args.mix)
        print(f"wrote {len(reqs)} requests to {args.out}")
    if args.summary or not args.out:
        lens = [len(r["prompt"]) for r in reqs]
        print(json.dumps({
            "mix": args.mix, "seed": args.seed, "requests": len(reqs),
            "batch_frac": (sum(r["priority"] == "batch" for r in reqs)
                           / len(reqs)) if reqs else 0.0,
            "prompt_len_mean": float(np.mean(lens)) if lens else 0.0,
            "span": reqs[-1]["at"] - reqs[0]["at"] if reqs else 0.0,
        }))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main(sys.argv[1:]))
