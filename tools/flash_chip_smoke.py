"""On-chip Mosaic compile/parity smoke for the flash kernel feature matrix.

The kv_mask / segment_ids / GQA / sliding-window operand plumbing is
interpret-mode tested on CPU; this script compiles and runs each feature
(and their composition) through the REAL Mosaic lowering on the local
TPU and checks parity vs the jnp reference — run it (via chip_queue)
before trusting the new kernel paths on hardware.

Usage: python tools/flash_chip_smoke.py [case ...]
Prints one JSON line per case. With args, runs only the named cases
("ring-blocks" selects the ring building-block set) — the round-4 run
showed the sliding-window compile can hang the remote compile helper
and wedge the rig, so chip_queue quarantines the window cases in their
own item AFTER everything else has measured.
"""

import json
import sys

sys.path.insert(0, ".")

KNOWN_CASES = {"plain", "kv_mask", "segments", "gqa", "window",
               "window+gqa+segs", "bwd-tiles", "ring-blocks",
               "ring-blocks-window"}
_unknown = set(sys.argv[1:]) - KNOWN_CASES
if _unknown:
    # a typo must not let the gating smoke "pass" with 0 cases run
    print(json.dumps({"error": f"unknown cases {sorted(_unknown)}",
                      "known": sorted(KNOWN_CASES)}), flush=True)
    sys.exit(2)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deepspeed_tpu.ops.attention import flash as F  # noqa: E402


def run_case(name, make):
    try:
        q, k, v, kwargs = make()
        # bwd tile overrides are a kernel knob only — strip for the ref
        ref_kwargs = {k_: v_ for k_, v_ in kwargs.items()
                      if not k_.startswith("bwd_")}
        out = jax.jit(lambda q, k, v: F.flash_attention(  # dslint: disable=DS002 — smoke test compiles per shape on purpose
            q, k, v, causal=True, block_q=256, block_kv=256,
            **kwargs))(q, k, v)
        ref = F.mha_reference(q, k, v, causal=True, **ref_kwargs)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                                    ref.astype(jnp.float32))))
        # backward too: grads through the custom VJP
        g = jax.grad(lambda q: (F.flash_attention(
            q, k, v, causal=True, block_q=256, block_kv=256,
            **kwargs) ** 2).sum())(q)
        gref = jax.grad(lambda q: (F.mha_reference(
            q, k, v, causal=True, **ref_kwargs) ** 2).sum())(q)
        gerr = float(jnp.max(jnp.abs(g - gref)))
        ok = err < 5e-2 and gerr < 5e-1   # bf16 tolerances
        print(json.dumps({"case": name, "ok": bool(ok),
                          "fwd_err": round(err, 5),
                          "dq_err": round(gerr, 5)}), flush=True)
    except Exception as e:
        print(json.dumps({"case": name, "ok": False,
                          "error": repr(e)[:300]}), flush=True)


def main():
    r = np.random.default_rng(0)
    B, S, H, D = 2, 1024, 8, 64

    def qkv(hkv=H):
        q = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.bfloat16)
        k = jnp.asarray(r.standard_normal((B, S, hkv, D)), jnp.bfloat16)
        v = jnp.asarray(r.standard_normal((B, S, hkv, D)), jnp.bfloat16)
        return q, k, v

    mask = jnp.asarray((r.random((B, S)) > 0.2).astype(np.float32))
    segs = jnp.asarray(np.repeat(np.arange(4), S // 4)[None].repeat(B, 0),
                       jnp.int32)

    cases = [
        ("plain", lambda: (*qkv(), {})),
        ("kv_mask", lambda: (*qkv(), {"kv_mask": mask})),
        ("segments", lambda: (*qkv(), {"segment_ids": segs})),
        ("gqa", lambda: (*qkv(hkv=2), {})),
        ("window", lambda: (*qkv(), {"window": 256})),
        ("window+gqa+segs", lambda: (*qkv(hkv=2),
                                     {"window": 256, "segment_ids": segs})),
        # round-3 addition: independent backward tiles through the VJP
        ("bwd-tiles", lambda: (*qkv(), {"bwd_block_q": 128,
                                        "bwd_block_kv": 128})),
    ]
    wanted = sys.argv[1:]
    assert {n for n, _ in cases} <= KNOWN_CASES   # keep the fast-fail list honest
    for name, make in cases:
        if not wanted or name in wanted:
            run_case(name, make)
    if (not wanted or "ring-blocks" in wanted
            or "ring-blocks-window" in wanted):
        ring_block_cases(wanted)


def ring_block_cases(wanted=()):
    """Mosaic-compile the ring building blocks (flash_block_fwd/bwd with
    a static q_off and separate kv-side segments) — the flash-grade ring
    (ops/attention/ring.py) stands on these; interpret mode cannot catch
    their lowering failures. The window sub-case runs only when
    'ring-blocks-window' is explicitly requested (see module docstring:
    window compiles are quarantined)."""
    r = np.random.default_rng(1)
    B, S, H, D = 1, 512, 4, 64
    q = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.bfloat16)
    k = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.bfloat16)
    v = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.bfloat16)
    # distinct q-side vs kv-side metadata (the rotated-block shape), but
    # every q row keeps >=1 matching key: rows with NO valid key are
    # garbage-by-contract (lse = -inf -> p = 1 everywhere in BOTH
    # implementations; their bf16-amplified grads differ meaninglessly
    # and the ring's global lse / loss mask excludes them in training)
    qsegs = jnp.asarray(np.repeat(np.arange(2), S // 2)[None], jnp.int32)
    ksegs = jnp.asarray(np.repeat([0, 1], [S // 4, 3 * S // 4])[None],
                        jnp.int32)

    for name, kwargs in [
        ("ring-block-offset", dict(causal=True, q_off=S)),
        ("ring-block-offset-window",
         dict(causal=True, q_off=S, window=S + 128)),
        ("ring-block-ksegs",
         dict(causal=True, q_off=S, q_segs=qsegs, kv_segs=ksegs)),
    ]:
        if "window" in name:
            if wanted and "ring-blocks-window" not in wanted:
                continue
        elif wanted and "ring-blocks" not in wanted:
            continue
        try:
            o, lse = jax.jit(lambda a, b, c: F.flash_block_fwd(  # dslint: disable=DS002 — benchmark measures per-config compile+run
                a, b, c, block_q=256, block_kv=256, **kwargs))(q, k, v)
            dq, dk, dv = jax.jit(lambda a, b, c, do, o, lse:  # dslint: disable=DS002 — benchmark measures per-config compile+run
                                 F.flash_block_bwd(
                                     a, b, c, do, o, lse, block_q=256,
                                     block_kv=256, **kwargs))(
                q, k, v, jnp.ones_like(q), o, lse)
            finite = all(bool(jnp.isfinite(x.astype(jnp.float32)).all())
                         for x in (o, lse, dq, dk, dv))
            # cross-check BOTH passes vs the jnp chunked block (the ring
            # fallback): a Mosaic miscompile that stays finite must not
            # slip through on 'ok': true
            from deepspeed_tpu.ops.attention.ring import (
                _jnp_block_bwd, _jnp_block_fwd)
            scale = 1.0 / np.sqrt(D)
            o_ref, lse_ref = _jnp_block_fwd(
                q, k, v, kwargs.get("q_segs"), kwargs.get("kv_segs"), None,
                blk_causal=kwargs["causal"], window=kwargs.get("window"),
                q_off=kwargs["q_off"], scale=scale, chunk=256)
            o_ref = o_ref.transpose(0, 2, 1, 3)     # kernel -> [B,S,H,D]
            err = float(jnp.max(jnp.abs(o.astype(jnp.float32) -
                                        o_ref.astype(jnp.float32))))
            do = jnp.ones_like(q)
            delta = jnp.sum(do.astype(jnp.float32) *
                            o.astype(jnp.float32),
                            axis=-1).transpose(0, 2, 1)     # [B,H,S]
            dq_r, dk_r, dv_r = _jnp_block_bwd(
                q, k, v, do, lse, delta, kwargs.get("q_segs"),
                kwargs.get("kv_segs"), None, blk_causal=kwargs["causal"],
                window=kwargs.get("window"), q_off=kwargs["q_off"],
                scale=scale, chunk=256)
            gerr = max(
                float(jnp.max(jnp.abs(dq.astype(jnp.float32) -
                                      dq_r.transpose(0, 2, 1, 3)))),
                float(jnp.max(jnp.abs(dk.astype(jnp.float32) -
                                      dk_r.transpose(0, 2, 1, 3)))),
                float(jnp.max(jnp.abs(dv.astype(jnp.float32) -
                                      dv_r.transpose(0, 2, 1, 3)))))
            print(json.dumps({"case": name,
                              "ok": bool(finite and err < 5e-2 and
                                         gerr < 5e-1),
                              "fwd_err_vs_jnp_block": round(err, 5),
                              "bwd_err_vs_jnp_block": round(gerr, 5)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"case": name, "ok": False,
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
