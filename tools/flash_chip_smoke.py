"""On-chip Mosaic compile/parity smoke for the flash kernel feature matrix.

The kv_mask / segment_ids / GQA / sliding-window operand plumbing is
interpret-mode tested on CPU; this script compiles and runs each feature
(and their composition) through the REAL Mosaic lowering on the local
TPU and checks parity vs the jnp reference — run it (via chip_queue)
before trusting the new kernel paths on hardware.

Usage: python tools/flash_chip_smoke.py
Prints one JSON line per case.
"""

import json
import sys

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deepspeed_tpu.ops.attention import flash as F  # noqa: E402


def run_case(name, make):
    try:
        q, k, v, kwargs = make()
        # bwd tile overrides are a kernel knob only — strip for the ref
        ref_kwargs = {k_: v_ for k_, v_ in kwargs.items()
                      if not k_.startswith("bwd_")}
        out = jax.jit(lambda q, k, v: F.flash_attention(
            q, k, v, causal=True, block_q=256, block_kv=256,
            **kwargs))(q, k, v)
        ref = F.mha_reference(q, k, v, causal=True, **ref_kwargs)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                                    ref.astype(jnp.float32))))
        # backward too: grads through the custom VJP
        g = jax.grad(lambda q: (F.flash_attention(
            q, k, v, causal=True, block_q=256, block_kv=256,
            **kwargs) ** 2).sum())(q)
        gref = jax.grad(lambda q: (F.mha_reference(
            q, k, v, causal=True, **ref_kwargs) ** 2).sum())(q)
        gerr = float(jnp.max(jnp.abs(g - gref)))
        ok = err < 5e-2 and gerr < 5e-1   # bf16 tolerances
        print(json.dumps({"case": name, "ok": bool(ok),
                          "fwd_err": round(err, 5),
                          "dq_err": round(gerr, 5)}), flush=True)
    except Exception as e:
        print(json.dumps({"case": name, "ok": False,
                          "error": repr(e)[:300]}), flush=True)


def main():
    r = np.random.default_rng(0)
    B, S, H, D = 2, 1024, 8, 64

    def qkv(hkv=H):
        q = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.bfloat16)
        k = jnp.asarray(r.standard_normal((B, S, hkv, D)), jnp.bfloat16)
        v = jnp.asarray(r.standard_normal((B, S, hkv, D)), jnp.bfloat16)
        return q, k, v

    mask = jnp.asarray((r.random((B, S)) > 0.2).astype(np.float32))
    segs = jnp.asarray(np.repeat(np.arange(4), S // 4)[None].repeat(B, 0),
                       jnp.int32)

    cases = [
        ("plain", lambda: (*qkv(), {})),
        ("kv_mask", lambda: (*qkv(), {"kv_mask": mask})),
        ("segments", lambda: (*qkv(), {"segment_ids": segs})),
        ("gqa", lambda: (*qkv(hkv=2), {})),
        ("window", lambda: (*qkv(), {"window": 256})),
        ("window+gqa+segs", lambda: (*qkv(hkv=2),
                                     {"window": 256, "segment_ids": segs})),
        # round-3 addition: independent backward tiles through the VJP
        ("bwd-tiles", lambda: (*qkv(), {"bwd_block_q": 128,
                                        "bwd_block_kv": 128})),
    ]
    for name, make in cases:
        run_case(name, make)


if __name__ == "__main__":
    main()
