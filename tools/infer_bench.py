"""On-chip inference benchmark: prefill + decode throughput/latency.

The reference's inference headline is kernel-injection latency speedups
(ref: deepspeed/inference/engine.py + docs/_tutorials/inference-tutorial.md
"2.3x faster GPT-2 latency on 1 GPU"). TPU analog measured here:

- prefill: tokens/s through the fused flash-prefill program;
- decode (host loop): per-token latency of the compiled, cache-donating
  decode step — pays one host round-trip per token;
- decode (fused): per-token latency inside `generate_fused` (the whole
  loop is ONE lax.scan program — the host round-trip amortizes away,
  which is the TPU-native answer to the reference's fused-kernel claim);
- feature matrix timings: GQA cache, sliding-window cache.

One JSON line per (config, mode). Guarded by the same per-item pattern
as chip_queue (fresh subprocess per config via tools/_subproc).

Usage: python tools/infer_bench.py [steps]
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request  # noqa: E402

honor_platform_request()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def bench_config(name, preset, batch, prompt_len, new_tokens,
                 n_kv_heads=None, attn_window=None, int8=False,
                 int8_fused=False):
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    # windowed rows use the "masked" impl: this bench runs in the
    # NON-quarantined queue item, and the banded window kernel's compile
    # is the known rig-wedger (PARITY.md note; tools/flash_window_bisect)
    cfg = gpt.preset(preset, max_seq_len=prompt_len + new_tokens + 8,
                     dtype=jnp.bfloat16, use_flash_attention=on_tpu,
                     n_kv_heads=n_kv_heads, attn_window=attn_window,
                     attn_window_impl="masked" if attn_window else None)
    if int8_fused:
        os.environ["DS_INT8_FUSED"] = "1"
    else:
        os.environ.pop("DS_INT8_FUSED", None)
    if on_tpu:
        # refuse borderline-HBM compiles before any backend contact
        # (utils/hbm.py, PERF.md incident log)
        from deepspeed_tpu.utils import hbm
        hbm.guard_infer_config(cfg, batch, cfg.max_seq_len)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = deepspeed_tpu.init_inference(
        model=(cfg, params),
        dtype=jnp.int8 if int8 else jnp.bfloat16)

    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, prompt_len)).astype(np.int32)

    # warmup at the MEASURED lengths: the fused scan executable is keyed
    # on n_steps, so a shorter warmup would leave the full compile inside
    # the timed call
    eng.generate(toks, max_new_tokens=new_tokens)
    eng.generate_fused(toks, max_new_tokens=new_tokens)

    # measured pass — report the engine's own per-token latencies, which
    # exclude prefill and compile by construction
    eng.generate(toks, max_new_tokens=new_tokens)
    host_ms = eng.latency_ms["decode_per_token"]
    eng.generate_fused(toks, max_new_tokens=new_tokens)
    fused_ms = eng.latency_ms["decode_per_token_fused"]

    print(json.dumps({
        "config": name, "preset": preset, "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "prefill_ms": round(eng.latency_ms.get("prefill", 0.0), 2),
        "decode_ms_per_token_hostloop": round(host_ms, 3),
        "decode_ms_per_token_fused": round(fused_ms, 3),
        "fused_speedup": round(host_ms / max(fused_ms, 1e-9), 2),
        "decode_tokens_per_s_fused": round(batch * 1e3 / fused_ms, 1),
    }), flush=True)


CONFIGS = [
    ("gpt2-medium-b8", dict(preset="gpt2-medium", batch=8,
                            prompt_len=512, new_tokens=64)),
    ("gpt2-medium-b32", dict(preset="gpt2-medium", batch=32,
                             prompt_len=512, new_tokens=64)),
    ("gpt2-large-b8", dict(preset="gpt2-large", batch=8,
                           prompt_len=512, new_tokens=64)),
    ("medium-gqa4", dict(preset="gpt2-medium", batch=8, prompt_len=512,
                         new_tokens=64, n_kv_heads=4)),
    ("medium-window256", dict(preset="gpt2-medium", batch=8,
                              prompt_len=512, new_tokens=64,
                              attn_window=256)),
    # weight-only int8: kernels at 1 byte/param — decode is HBM-bound
    # on weight reads, so this targets the reference's int8 inference
    # claim (vs the bf16 gpt2-medium-b8 row)
    ("gpt2-medium-b8-int8", dict(preset="gpt2-medium", batch=8,
                                 prompt_len=512, new_tokens=64,
                                 int8=True)),
    # same row through the Pallas fused dequant-matmul (VERDICT r4 weak
    # #6): if XLA's dequant fusion already recovers the bandwidth win
    # this ties the row above; if not, this is the shipping fallback
    ("gpt2-medium-b8-int8-fused", dict(preset="gpt2-medium", batch=8,
                                       prompt_len=512, new_tokens=64,
                                       int8=True, int8_fused=True)),
]


def bench_speculative(name, target_preset, draft_preset, batch,
                      prompt_len, new_tokens, gamma):
    """Speculative vs plain greedy decode on the same target: wall-clock
    tokens/s for identical output (the greedy exactness contract)."""
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.speculative import generate_speculative

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    mk = lambda preset: gpt.preset(
        preset, max_seq_len=prompt_len + new_tokens + gamma + 8,
        dtype=jnp.bfloat16, use_flash_attention=on_tpu)
    cfg_t, cfg_d = mk(target_preset), mk(draft_preset)
    if on_tpu:
        # BOTH engines are resident simultaneously: guard the SUM of
        # their footprints, not each alone
        from deepspeed_tpu.utils import hbm
        est = hbm.estimate_infer_bytes(cfg_t, batch, cfg_t.max_seq_len)
        est_d = hbm.estimate_infer_bytes(cfg_d, batch, cfg_d.max_seq_len)
        for k, v in est_d.contributions.items():
            est.contributions[f"draft_{k}"] = v
        hbm._guard(est, None, hbm.DEFAULT_HEADROOM_GIB)
    t_eng = deepspeed_tpu.init_inference(
        model=(cfg_t, gpt.init_params(jax.random.PRNGKey(0), cfg_t)),
        dtype=jnp.bfloat16)
    d_eng = deepspeed_tpu.init_inference(
        model=(cfg_d, gpt.init_params(jax.random.PRNGKey(1), cfg_d)),
        dtype=jnp.bfloat16)
    toks = np.random.default_rng(0).integers(
        0, cfg_t.vocab_size, (batch, prompt_len)).astype(np.int32)
    # warmup both paths (compiles)
    t_eng.generate(toks, max_new_tokens=new_tokens)
    generate_speculative(t_eng, d_eng, toks, max_new_tokens=new_tokens,
                         gamma=gamma)
    t0 = time.perf_counter()
    ref = t_eng.generate(toks, max_new_tokens=new_tokens)
    plain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    got, stats = generate_speculative(t_eng, d_eng, toks,
                                      max_new_tokens=new_tokens,
                                      gamma=gamma, return_stats=True)
    spec_s = time.perf_counter() - t0
    print(json.dumps({
        "config": name, "target": target_preset, "draft": draft_preset,
        "batch": batch, "gamma": gamma, "output_identical":
        bool((got == ref).all()),
        "plain_tokens_per_s": round(batch * new_tokens / plain_s, 1),
        "spec_tokens_per_s": round(batch * new_tokens / spec_s, 1),
        "speedup": round(plain_s / spec_s, 2),
        "accepted_per_round": round(stats["accepted_per_round"], 2),
    }), flush=True)


SPEC_CONFIGS = [
    ("spec-large-from-small", dict(target_preset="gpt2-large",
                                   draft_preset="gpt2-small", batch=1,
                                   prompt_len=128, new_tokens=64,
                                   gamma=4)),
]


def main():
    from deepspeed_tpu.utils.hbm import MemoryGuardError
    for name, kw in CONFIGS:
        try:
            bench_config(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)
    for name, kw in SPEC_CONFIGS:
        try:
            bench_speculative(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
