"""On-chip inference benchmark: prefill + decode throughput/latency.

The reference's inference headline is kernel-injection latency speedups
(ref: deepspeed/inference/engine.py + docs/_tutorials/inference-tutorial.md
"2.3x faster GPT-2 latency on 1 GPU"). TPU analog measured here:

- prefill: tokens/s through the fused flash-prefill program;
- decode (host loop): per-token latency of the compiled, cache-donating
  decode step — pays one host round-trip per token;
- decode (fused): per-token latency inside `generate_fused` (the whole
  loop is ONE lax.scan program — the host round-trip amortizes away,
  which is the TPU-native answer to the reference's fused-kernel claim);
- feature matrix timings: GQA cache, sliding-window cache.

One JSON line per (config, mode). Guarded by the same per-item pattern
as chip_queue (fresh subprocess per config via tools/_subproc).

Usage: python tools/infer_bench.py [steps]
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request  # noqa: E402

honor_platform_request()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def bench_config(name, preset, batch, prompt_len, new_tokens,
                 n_kv_heads=None, attn_window=None, int8=False,
                 int8_fused=False):
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    # windowed rows use the "masked" impl: this bench runs in the
    # NON-quarantined queue item, and the banded window kernel's compile
    # is the known rig-wedger (PARITY.md note; tools/flash_window_bisect)
    cfg = gpt.preset(preset, max_seq_len=prompt_len + new_tokens + 8,
                     dtype=jnp.bfloat16, use_flash_attention=on_tpu,
                     n_kv_heads=n_kv_heads, attn_window=attn_window,
                     attn_window_impl="masked" if attn_window else None)
    if int8_fused:
        os.environ["DS_INT8_FUSED"] = "1"
    else:
        os.environ.pop("DS_INT8_FUSED", None)
    if on_tpu:
        # refuse borderline-HBM compiles before any backend contact
        # (utils/hbm.py, PERF.md incident log)
        from deepspeed_tpu.utils import hbm
        hbm.guard_infer_config(cfg, batch, cfg.max_seq_len)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = deepspeed_tpu.init_inference(
        model=(cfg, params),
        dtype=jnp.int8 if int8 else jnp.bfloat16)

    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, prompt_len)).astype(np.int32)

    # warmup at the MEASURED lengths: the fused scan executable is keyed
    # on n_steps, so a shorter warmup would leave the full compile inside
    # the timed call
    eng.generate(toks, max_new_tokens=new_tokens)
    eng.generate_fused(toks, max_new_tokens=new_tokens)

    # measured pass — report the engine's own per-token latencies, which
    # exclude prefill and compile by construction
    eng.generate(toks, max_new_tokens=new_tokens)
    host_ms = eng.latency_ms["decode_per_token"]
    eng.generate_fused(toks, max_new_tokens=new_tokens)
    fused_ms = eng.latency_ms["decode_per_token_fused"]

    print(json.dumps({
        "config": name, "preset": preset, "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "prefill_ms": round(eng.latency_ms.get("prefill", 0.0), 2),
        "decode_ms_per_token_hostloop": round(host_ms, 3),
        "decode_ms_per_token_fused": round(fused_ms, 3),
        "fused_speedup": round(host_ms / max(fused_ms, 1e-9), 2),
        "decode_tokens_per_s_fused": round(batch * 1e3 / fused_ms, 1),
    }), flush=True)


CONFIGS = [
    ("gpt2-medium-b8", dict(preset="gpt2-medium", batch=8,
                            prompt_len=512, new_tokens=64)),
    ("gpt2-medium-b32", dict(preset="gpt2-medium", batch=32,
                             prompt_len=512, new_tokens=64)),
    ("gpt2-large-b8", dict(preset="gpt2-large", batch=8,
                           prompt_len=512, new_tokens=64)),
    ("medium-gqa4", dict(preset="gpt2-medium", batch=8, prompt_len=512,
                         new_tokens=64, n_kv_heads=4)),
    ("medium-window256", dict(preset="gpt2-medium", batch=8,
                              prompt_len=512, new_tokens=64,
                              attn_window=256)),
    # weight-only int8: kernels at 1 byte/param — decode is HBM-bound
    # on weight reads, so this targets the reference's int8 inference
    # claim (vs the bf16 gpt2-medium-b8 row)
    ("gpt2-medium-b8-int8", dict(preset="gpt2-medium", batch=8,
                                 prompt_len=512, new_tokens=64,
                                 int8=True)),
    # same row through the Pallas fused dequant-matmul (VERDICT r4 weak
    # #6): if XLA's dequant fusion already recovers the bandwidth win
    # this ties the row above; if not, this is the shipping fallback
    ("gpt2-medium-b8-int8-fused", dict(preset="gpt2-medium", batch=8,
                                       prompt_len=512, new_tokens=64,
                                       int8=True, int8_fused=True)),
]


def bench_speculative(name, target_preset, draft_preset, batch,
                      prompt_len, new_tokens, gamma):
    """Speculative vs plain greedy decode on the same target: wall-clock
    tokens/s for identical output (the greedy exactness contract)."""
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.speculative import generate_speculative

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    mk = lambda preset: gpt.preset(
        preset, max_seq_len=prompt_len + new_tokens + gamma + 8,
        dtype=jnp.bfloat16, use_flash_attention=on_tpu)
    cfg_t, cfg_d = mk(target_preset), mk(draft_preset)
    if on_tpu:
        # BOTH engines are resident simultaneously: guard the SUM of
        # their footprints, not each alone
        from deepspeed_tpu.utils import hbm
        est = hbm.estimate_infer_bytes(cfg_t, batch, cfg_t.max_seq_len)
        est_d = hbm.estimate_infer_bytes(cfg_d, batch, cfg_d.max_seq_len)
        for k, v in est_d.contributions.items():
            est.contributions[f"draft_{k}"] = v
        hbm._guard(est, None, hbm.DEFAULT_HEADROOM_GIB)
    t_eng = deepspeed_tpu.init_inference(
        model=(cfg_t, gpt.init_params(jax.random.PRNGKey(0), cfg_t)),
        dtype=jnp.bfloat16)
    d_eng = deepspeed_tpu.init_inference(
        model=(cfg_d, gpt.init_params(jax.random.PRNGKey(1), cfg_d)),
        dtype=jnp.bfloat16)
    toks = np.random.default_rng(0).integers(
        0, cfg_t.vocab_size, (batch, prompt_len)).astype(np.int32)
    # warmup both paths (compiles)
    t_eng.generate(toks, max_new_tokens=new_tokens)
    generate_speculative(t_eng, d_eng, toks, max_new_tokens=new_tokens,
                         gamma=gamma)
    t0 = time.perf_counter()
    ref = t_eng.generate(toks, max_new_tokens=new_tokens)
    plain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    got, stats = generate_speculative(t_eng, d_eng, toks,
                                      max_new_tokens=new_tokens,
                                      gamma=gamma, return_stats=True)
    spec_s = time.perf_counter() - t0
    print(json.dumps({
        "config": name, "target": target_preset, "draft": draft_preset,
        "batch": batch, "gamma": gamma, "output_identical":
        bool((got == ref).all()),
        "plain_tokens_per_s": round(batch * new_tokens / plain_s, 1),
        "spec_tokens_per_s": round(batch * new_tokens / spec_s, 1),
        "speedup": round(plain_s / spec_s, 2),
        "accepted_per_round": round(stats["accepted_per_round"], 2),
    }), flush=True)


SPEC_CONFIGS = [
    ("spec-large-from-small", dict(target_preset="gpt2-large",
                                   draft_preset="gpt2-small", batch=1,
                                   prompt_len=128, new_tokens=64,
                                   gamma=4)),
]


def bench_serving(name, preset=None, num_requests=16, mean_gap_steps=2.0,
                  prompt_lens=(8, 48), new_tokens=24, num_slots=4,
                  block_size=16, num_blocks=None, prefill_chunk=32,
                  int8=False, int8_fused=False, seed=0, decode_impl=None,
                  prefix_cache=None, shared_prefix_len=0,
                  spec_decode=None, spec_k=None, kv_quant=None,
                  host_tier=None, host_budget_bytes=None,
                  spill_watermark=None, prefix_families=1,
                  temperature=0.0, top_p=1.0, sample_seed=0,
                  decode_horizon=None, chip_peak_flops=None, emit=True):
    """Continuous-batching serving row: synthetic Poisson arrivals driven
    through ServingEngine.step, wall-clock tokens/s, TTFT/TPOT latency
    percentiles from the telemetry registry's histograms, decode-slot
    utilization, and the paged-vs-static KV HBM accounting.

    Arrivals are in SCHEDULER-STEP units (deterministic under ``seed``):
    request i is submitted before the first step >= its exponential-gap
    cumsum. ``preset=None`` runs a CPU-smoke-sized model.

    ``decode_impl`` pins the paged attention path ("gather" | "pallas",
    None = platform default); every row reports which one actually ran
    plus the analytic cache HBM traffic per decoded token for that path
    (the gather path moves the whole virtual cache 3x; pallas reads only
    occupied blocks, once). Returns the row dict so the impl-comparison
    row can reuse it (``emit=False`` suppresses the JSON line).

    ``shared_prefix_len`` > 0 prepends a fixed system prompt to every
    request (the shared-prefix workload); ``prefix_cache`` pins the
    shared-prefix KV cache on/off (None = ``DS_PREFIX_CACHE``). Rows
    report ``prefix_hit_rate``/``prefix_tokens_saved``/``prefill_chunks``
    so the on/off comparison shows the prefill work the cache removes.

    ``spec_decode``/``spec_k`` pin speculative decoding inside the batch
    (None = ``DS_SPEC_DECODE``/``DS_SPEC_K``); rows report the registry-
    sourced ``accept_rate`` (drafts the target agreed with) and
    ``tokens_per_step`` (emitted per slot per verify step — the
    speculative speedup factor; 1.0 with speculation off).

    ``kv_quant`` pins int8 KV-cache block quantization ("int8" | "off",
    None = ``DS_KV_QUANT``). The HBM columns are derived from the
    ACTUAL pool dtype plus the per-block scale overhead, and
    ``slots_admittable`` reports how many decode slots the unquantized
    pool's HBM budget admits at the row's pool layout — the capacity-
    per-chip headline (~2x for int8 over bf16).

    ``host_tier`` pins the host-DRAM KV second tier on/off (None =
    ``DS_KV_HOST_TIER``); ``prefix_families`` > 1 rotates requests
    through that many DISTINCT system prompts in two passes each, so a
    family's chain goes cold between visits — at a constrained
    ``num_blocks`` the device-only cache must evict it, while the host
    tier spills and restores it (``spill_watermark`` pins the daemon's
    pressure threshold). Rows report the host transfer counters.

    ``decode_horizon`` pins the fused multi-step decode horizon N
    (None = ``DS_DECODE_HORIZON``, docs/MULTISTEP.md); rows split
    ``ms_per_token`` into ``host_ms_per_token`` vs
    ``device_ms_per_token`` (device = wall seconds the engine spent
    inside device dispatch + harvest, host = the scheduler-loop rest)
    so the ~N× host amortization is visible even on CPU.

    ``temperature``/``top_p`` > defaults turn the drive into a SAMPLED
    workload (every request seeded ``sample_seed + rid``, so a row is
    reproducible run-to-run); rows report ``sampled``/``temperature``/
    ``top_p`` plus the ``sampled_tokens`` counter, and the fused
    in-program sampler keeps the compile/latency profile of the greedy
    drive (docs/SAMPLING.md).
    """
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.telemetry import Telemetry

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_seq = prompt_lens[1] + shared_prefix_len + new_tokens + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    if int8_fused:
        os.environ["DS_INT8_FUSED"] = "1"
    else:
        os.environ.pop("DS_INT8_FUSED", None)
    act_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    eng = deepspeed_tpu.init_inference(
        model=(cfg, gpt.init_params(jax.random.PRNGKey(0), cfg)),
        dtype=jnp.int8 if int8 else act_dtype)
    # telemetry on for the timed drive: the latency columns come from
    # the registry's TTFT/TPOT histograms (scheduler clock = perf_counter
    # seconds here), not from ad-hoc timestamp lists
    srv = ServingEngine(eng, num_slots=num_slots, block_size=block_size,
                        num_blocks=num_blocks, prefill_chunk=prefill_chunk,
                        decode_impl=decode_impl, prefix_cache=prefix_cache,
                        spec_decode=spec_decode, spec_k=spec_k,
                        kv_quant=kv_quant, host_tier=host_tier,
                        host_budget_bytes=host_budget_bytes,
                        spill_watermark=spill_watermark,
                        decode_horizon=decode_horizon,
                        telemetry=Telemetry())

    rng = np.random.default_rng(seed)
    arrive = np.floor(np.cumsum(
        rng.exponential(mean_gap_steps, num_requests))).astype(int)
    # the shared-prefix workload: requests open with a deterministic
    # system prompt (independent of the tail rng stream). family 0 is
    # bit-identical to the single-family formula; prefix_families > 1
    # rotates groups A A.. B B.. A A.. so chains go cold between visits
    if not shared_prefix_len:
        fams = None
    elif prefix_families <= 1:
        fams = [(1 + np.arange(shared_prefix_len)
                 % (cfg.vocab_size - 1)).astype(np.int32)]
    else:
        fams = [((1 + 131 * f + np.arange(shared_prefix_len))
                 % (cfg.vocab_size - 1)).astype(np.int32)
                for f in range(prefix_families)]
    group = max(1, -(-num_requests // (2 * max(1, prefix_families))))

    def mk_prompt(i):
        tail = rng.integers(0, cfg.vocab_size,
                            rng.integers(*prompt_lens)).astype(np.int32)
        if fams is None:
            return tail
        sys_prompt = fams[(i // group) % len(fams)]
        return np.concatenate([sys_prompt, tail])

    reqs = [ServeRequest(rid=i, prompt=mk_prompt(i),
                         max_new_tokens=new_tokens,
                         temperature=temperature, top_p=top_p,
                         seed=sample_seed + i)
            for i in range(num_requests)]

    # warmup: compile both slot programs before the timed drive
    w = ServingEngine(eng, num_slots=num_slots, block_size=block_size,
                      num_blocks=num_blocks, prefill_chunk=prefill_chunk,
                      decode_impl=decode_impl, prefix_cache=prefix_cache,
                      spec_decode=spec_decode, spec_k=spec_k,
                      kv_quant=kv_quant, host_tier=host_tier,
                      host_budget_bytes=host_budget_bytes,
                      spill_watermark=spill_watermark,
                      decode_horizon=decode_horizon)
    w.run([ServeRequest(rid="w", prompt=reqs[0].prompt.copy(),
                        max_new_tokens=2)])

    # device-time via the snapshot/delta idiom: device_time_s is a
    # monotonic accumulator over the engine's lifetime, so a drive must
    # bill itself the DELTA, not the running total — reusing one engine
    # for k repeats would otherwise double-bill every repeat
    dev0 = srv.device_time_snapshot()
    t0 = time.perf_counter()
    step = 0
    nxt = 0
    while nxt < num_requests or srv.busy:
        while nxt < num_requests and arrive[nxt] <= step:
            srv.submit(reqs[nxt], now=time.perf_counter())
            nxt += 1
        srv.step(now=time.perf_counter())
        step += 1
    wall_s = time.perf_counter() - t0
    device_s = srv.device_time_snapshot() - dev0

    ttft_h = srv.metrics.histogram("serving_ttft")
    tpot_h = srv.metrics.histogram("serving_tpot")
    gen_tokens = sum(len(r.out) for r in srv.finished)
    st = srv.stats
    cache = srv.cache
    # per-block bytes from the ACTUAL pool dtype (int8 under kv_quant)
    # plus the fp32 per-block scale sidecar — not the activation dtype
    blk_bytes = cache.bytes_per_token * block_size \
        + cache.scale_bytes_per_block
    # capacity at fixed HBM: the budget the UNQUANTIZED pool would spend
    # on num_slots full slots, re-divided by the row's actual per-slot
    # cost — bf16/fp32 rows report num_slots back, int8 rows ~2x it
    fp_slot_bytes = cache.blocks_per_slot * block_size \
        * gpt.kv_bytes_per_token(cfg, cache.dtype)
    slots_admittable = int(num_slots * fp_slot_bytes
                           // (cache.blocks_per_slot * blk_bytes))
    from deepspeed_tpu.ops.attention.paged import paged_hbm_bytes_per_token
    mean_len = float(np.mean([len(r.prompt) + len(r.out) / 2
                              for r in srv.finished])) if srv.finished else 0
    # serve-cost-* attribution columns (telemetry/costs.py): the exact
    # integer FLOPs/HBM bytes the accountant charged this drive, the
    # analytic per-token model cost, and a roofline MFU against the
    # chip peak (``chip_peak_flops`` overrides; default = this device's
    # spec-sheet peak, None on CPU -> mfu_analytic null)
    from deepspeed_tpu.telemetry.costs import (device_peak_flops,
                                               model_flops_per_token)
    cost_snap = srv.costs.snapshot() if srv.costs.enabled else None
    peak = (chip_peak_flops if chip_peak_flops is not None
            else device_peak_flops())
    cost_flops = cost_snap["flops_total"] if cost_snap else 0
    row = {
        "config": name, "preset": preset or "cpu-smoke",
        "num_requests": num_requests, "new_tokens": new_tokens,
        "num_slots": num_slots, "block_size": block_size,
        "decode_impl": srv.decode_impl,
        "tokens_per_s": round(gen_tokens / wall_s, 1),
        "tpot_ms_p50": round(tpot_h.percentile(50) * 1e3, 3),
        "tpot_ms_p99": round(tpot_h.percentile(99) * 1e3, 3),
        "ttft_p50_ms": round(ttft_h.percentile(50) * 1e3, 3),
        "ttft_p99_ms": round(ttft_h.percentile(99) * 1e3, 3),
        "tpot_p50_ms": round(tpot_h.percentile(50) * 1e3, 3),
        "mean_occupancy": round(st["occupancy_sum"]
                                / max(st["decode_steps"], 1), 2),
        "peak_occupancy": st["peak_occupancy"],
        "slot_utilization": round(st["occupancy_sum"]
                                  / (max(st["steps"], 1) * num_slots), 2),
        "evictions": st["evictions"],
        "peak_kv_bytes_paged": int(cache.peak_used_blocks * blk_bytes),
        "static_kv_bytes": int(cache.static_equivalent_bytes(num_slots)),
        "kv_hbm_bytes_per_token": paged_hbm_bytes_per_token(
            cfg, num_slots, mean_len, cache.tokens_per_slot,
            dtype=cache.pool_dtype, impl=srv.decode_impl,
            block_size=block_size,
            scale_bytes_per_block=cache.scale_bytes_per_block),
        # int8 KV-cache columns: pool dtype actually allocated, write
        # bytes per cached token (pool + amortized scale sidecar), and
        # the fixed-budget slot capacity defined above
        "kv_quant": srv.kv_quant,
        "kv_pool_dtype": str(np.dtype(cache.pool_dtype)),
        "kv_cache_bytes_per_token": round(
            cache.bytes_per_token
            + cache.scale_bytes_per_block / block_size, 1),
        "slots_admittable": slots_admittable,
        "completed": st["completed"],
        # robustness counters: zero in a clean run, nonzero under
        # deadlines/bounded queues/chaos (DS_FAULTS) — a bench row that
        # silently dropped work would otherwise report inflated tokens/s
        "timeouts": st["timeouts"],
        "shed": st["shed"],
        "evict_capped": st["evict_capped"],
        # shared-prefix KV cache columns: hit rate over admissions,
        # prompt tokens whose prefill was skipped, and total prefill
        # chunks (the on/off delta is the work the cache removed)
        "prefix_cache": bool(srv.prefix_cache),
        "prefix_hit_rate": round(
            st["prefix_hits"] / max(st["admitted"], 1), 3),
        "prefix_tokens_saved": st["prefix_tokens_saved"],
        "prefill_chunks": st["prefill_chunks"],
        # host-DRAM KV tier columns (all zero with the tier off): how
        # many cold prefix blocks were spilled off-device, how many a
        # later prefix hit pulled back instead of re-prefilling, and
        # restores the CRC/fault degrade path turned into cold misses
        "host_tier": bool(srv.host_tier),
        "prefix_families": prefix_families,
        "host_spills": cache.host_spills,
        "host_restores": cache.host_restores,
        "host_restore_failures": cache.host_restore_failures,
        "host_blocks": cache.host_blocks,
        "host_bytes": cache.host_bytes,
        # speculative-decode columns, registry-sourced: accept_rate is
        # drafts-the-target-agreed-with over drafts offered;
        # tokens_per_step is emitted tokens per slot per verify step
        # (the speedup factor — 1.0 exactly when speculation is off);
        # ms_per_token is the TPOT histogram mean, the wall-clock the
        # acceptance actually buys down
        # sampling columns: whether the drive sampled (temperature>0),
        # the knobs, and how many emitted tokens came off sampled lanes
        "sampled": temperature > 0.0,
        "temperature": temperature,
        "top_p": top_p,
        "sampled_tokens": st["sampled_tokens"],
        "spec_decode": bool(srv.spec_decode),
        "spec_k": srv.spec_k if srv.spec_decode else 0,
        "decode_steps": st["decode_steps"],
        "accept_rate": round(
            st["spec_accepted"] / max(st["spec_proposed"], 1), 3),
        "tokens_per_step": round(
            st["spec_emitted"] / st["spec_slot_steps"], 2)
        if st["spec_slot_steps"] else 1.0,
        "spec_fallbacks": st["spec_fallbacks"],
        "ms_per_token": round(tpot_h.sum / tpot_h.count * 1e3, 3)
        if tpot_h.count else 0.0,
        # host/device wall split (docs/MULTISTEP.md): device is the
        # wall time spent inside device dispatch + harvest pulls, host
        # is everything else the scheduler loop did — the horizon
        # amortizes the host share ~N×
        "decode_horizon": srv.decode_horizon,
        "device_ms_per_token": round(
            device_s / max(gen_tokens, 1) * 1e3, 3),
        "host_ms_per_token": round(
            max(0.0, wall_s - device_s)
            / max(gen_tokens, 1) * 1e3, 3),
        "horizon_fallbacks": st["horizon_fallbacks"],
        "model_flops_per_token": model_flops_per_token(cfg),
        "serve_cost_flops_total": cost_flops,
        "serve_cost_hbm_bytes_total": (cost_snap["hbm_bytes_total"]
                                       if cost_snap else 0),
        "serve_cost_kv_block_seconds": (cost_snap["block_seconds_total"]
                                        if cost_snap else 0),
        "serve_cost_flops_per_token": round(
            cost_flops / max(gen_tokens, 1), 1),
        "chip_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu_analytic": round(cost_flops / device_s / peak, 4)
        if (peak and device_s > 0) else None,
        "cache_stats": cache.stats(),
        # per-request lifecycle timestamps (seconds relative to drive
        # start): submit/first-token/finish per rid, so SLO attainment
        # under any TTFT budget is recomputable OFFLINE from the row —
        # the aggregate percentiles above are a digest, not the record
        "requests_detail": [
            {"rid": r.rid,
             "submitted_at": round(r.submitted_at - t0, 6)
             if r.submitted_at is not None else None,
             "first_token_at": round(r.first_token_at - t0, 6)
             if r.first_token_at is not None else None,
             "finished_at": round(r.finished_at - t0, 6)
             if r.finished_at is not None else None,
             "state": r.state, "generated": len(r.out)}
            for r in srv.finished],
    }
    if emit:
        print(json.dumps(row), flush=True)
    # greedy streams for comparison rows (post-emit: never serialized)
    row["_results"] = {r.rid: r.tokens.tolist() for r in srv.finished}
    return row


def bench_serving_impl_compare(name, **kw):
    """Same serving drive under both paged-decode attention paths:
    gather (dense virtual-cache copy per token) vs pallas (flash-decode
    through the block table). Greedy streams must be identical; the row
    is the decode-latency and cache-traffic delta the kernel buys."""
    g = bench_serving(f"{name}[gather]", decode_impl="gather", **kw)
    p = bench_serving(f"{name}[pallas]", decode_impl="pallas", **kw)
    print(json.dumps({
        "config": name, "preset": g["preset"],
        "decode_impl": "gather-vs-pallas",
        "tpot_ms_p50_gather": g["tpot_ms_p50"],
        "tpot_ms_p50_pallas": p["tpot_ms_p50"],
        "tpot_speedup": round(g["tpot_ms_p50"]
                              / max(p["tpot_ms_p50"], 1e-9), 2),
        "tokens_per_s_gather": g["tokens_per_s"],
        "tokens_per_s_pallas": p["tokens_per_s"],
        "kv_hbm_bytes_per_token_gather": g["kv_hbm_bytes_per_token"],
        "kv_hbm_bytes_per_token_pallas": p["kv_hbm_bytes_per_token"],
        "hbm_traffic_ratio": round(
            g["kv_hbm_bytes_per_token"]
            / max(p["kv_hbm_bytes_per_token"], 1), 1),
    }), flush=True)


def bench_serving_prefix_compare(name, shared_prefix_len=64, **kw):
    """Same shared-system-prompt drive with the prefix cache OFF then
    ON: greedy streams must be identical (the cache changes work done,
    never tokens produced); the row is the prefill work and KV-sharing
    delta the cache buys."""
    off = bench_serving(f"{name}[off]", prefix_cache=False,
                        shared_prefix_len=shared_prefix_len, **kw)
    on = bench_serving(f"{name}[on]", prefix_cache=True,
                       shared_prefix_len=shared_prefix_len, **kw)
    print(json.dumps({
        "config": name, "preset": off["preset"],
        "prefix_cache": "off-vs-on",
        "shared_prefix_len": shared_prefix_len,
        "output_identical": off["_results"] == on["_results"],
        "prefix_hit_rate": on["prefix_hit_rate"],
        "prefix_tokens_saved": on["prefix_tokens_saved"],
        "prefill_chunks_off": off["prefill_chunks"],
        "prefill_chunks_on": on["prefill_chunks"],
        "prefill_chunks_saved": off["prefill_chunks"]
        - on["prefill_chunks"],
        "tokens_per_s_off": off["tokens_per_s"],
        "tokens_per_s_on": on["tokens_per_s"],
        "cow_copies": on["cache_stats"]["cow_copies"],
    }), flush=True)


def bench_serving_horizon_compare(name, horizons=(1, 4, 8), repeats=1,
                                  **kw):
    """Same drive at fused decode horizons N ∈ ``horizons``: token
    streams must be identical at every N (the docs/MULTISTEP.md
    bit-parity contract — the horizon changes how many host round-trips
    the same tokens take, never the tokens); the row is the host-side
    ms/token the fusion amortizes, one scheduler iteration per horizon
    instead of per token. On CPU the "device" program is itself
    host-executed, so device_ms dominates and the host_amortization
    column understates the on-chip win (the ROADMAP chip-queue entry).

    ``repeats`` runs each N's drive that many times and keeps the MIN
    of the timing columns — the large-N host deltas are single-digit
    µs/token on the CPU smoke configs, inside one trial's OS jitter,
    and min-of-k is the standard way to read a floor through noise.
    Stream identity is checked on every repeat."""
    rows = []
    for n in horizons:
        best = None
        for r_i in range(max(1, int(repeats))):
            r = bench_serving(f"{name}[n{n}]" if repeats <= 1
                              else f"{name}[n{n} r{r_i}]",
                              decode_horizon=n, **kw)
            if best is None:
                best = r
            else:
                assert r["_results"] == best["_results"], \
                    f"{name}[n{n}]: stream varied across repeats"
                for col in ("host_ms_per_token", "device_ms_per_token",
                            "ms_per_token"):
                    best[col] = min(best[col], r[col])
                best["tokens_per_s"] = max(best["tokens_per_s"],
                                           r["tokens_per_s"])
        rows.append(best)
    base = rows[0]
    out = {
        "config": name, "preset": base["preset"],
        "decode_horizon": "-vs-".join(str(n) for n in horizons),
        "output_identical": all(r["_results"] == base["_results"]
                                for r in rows[1:]),
    }
    for n, r in zip(horizons, rows):
        out[f"host_ms_per_token_n{n}"] = r["host_ms_per_token"]
        out[f"device_ms_per_token_n{n}"] = r["device_ms_per_token"]
        out[f"tokens_per_s_n{n}"] = r["tokens_per_s"]
    out["host_amortization"] = round(
        base["host_ms_per_token"]
        / max(rows[-1]["host_ms_per_token"], 1e-9), 2)
    print(json.dumps(out), flush=True)
    return out


def bench_serving_hosttier_compare(name, shared_prefix_len=24,
                                   prefix_families=3, num_blocks=None,
                                   spill_watermark=None, **kw):
    """Same multi-family shared-prefix drive at the SAME constrained
    device pool, host tier OFF then ON: greedy streams must be
    identical (the tier changes where cold prefix bytes live, never
    the tokens produced); the row is the prefix hit rate the host tier
    recovers at fixed HBM — chains the device-only cache must evict to
    admit the next family instead spill to host DRAM and restore when
    the family returns."""
    off = bench_serving(f"{name}[off]", prefix_cache=True,
                        host_tier=False,
                        shared_prefix_len=shared_prefix_len,
                        prefix_families=prefix_families,
                        num_blocks=num_blocks, **kw)
    on = bench_serving(f"{name}[on]", prefix_cache=True, host_tier=True,
                       shared_prefix_len=shared_prefix_len,
                       prefix_families=prefix_families,
                       num_blocks=num_blocks,
                       spill_watermark=spill_watermark, **kw)
    print(json.dumps({
        "config": name, "preset": off["preset"],
        "host_tier": "off-vs-on",
        "shared_prefix_len": shared_prefix_len,
        "prefix_families": prefix_families,
        "num_blocks": num_blocks,
        "output_identical": off["_results"] == on["_results"],
        "prefix_hit_rate_off": off["prefix_hit_rate"],
        "prefix_hit_rate_on": on["prefix_hit_rate"],
        "prefix_tokens_saved_off": off["prefix_tokens_saved"],
        "prefix_tokens_saved_on": on["prefix_tokens_saved"],
        "host_spills": on["host_spills"],
        "host_restores": on["host_restores"],
        "host_restore_failures": on["host_restore_failures"],
        "host_bytes": on["host_bytes"],
        "tokens_per_s_off": off["tokens_per_s"],
        "tokens_per_s_on": on["tokens_per_s"],
    }), flush=True)


def bench_serving_spec_compare(name, **kw):
    """Same serving drive with speculative decoding OFF then ON: greedy
    streams must be identical (acceptance is target-argmax equality, so
    speculation changes step count, never tokens), and the row is the
    acceptance and per-token-latency delta the draft/verify loop buys."""
    off = bench_serving(f"{name}[off]", spec_decode=False, **kw)
    on = bench_serving(f"{name}[on]", spec_decode=True, **kw)
    print(json.dumps({
        "config": name, "preset": off["preset"],
        "spec_decode": "off-vs-on", "spec_k": on["spec_k"],
        "output_identical": off["_results"] == on["_results"],
        "accept_rate": on["accept_rate"],
        "tokens_per_step": on["tokens_per_step"],
        "spec_fallbacks": on["spec_fallbacks"],
        "decode_steps_off": off["decode_steps"],
        "decode_steps_on": on["decode_steps"],
        "ms_per_token_off": off["ms_per_token"],
        "ms_per_token_on": on["ms_per_token"],
        "tokens_per_s_off": off["tokens_per_s"],
        "tokens_per_s_on": on["tokens_per_s"],
    }), flush=True)


def bench_serving_sampling_compare(name, temperature=0.9, top_p=0.95,
                                   **kw):
    """The same serving drive greedy, sampled, and sampled with
    speculative decoding on. Three contracts in one row: the sampled
    drive replays bit-identically under the same per-request seeds
    (the key chain is pure data), the sampled-spec drive routes
    drafted slots through the rejection-sampling verify (accept_rate
    reports how often the target agreed — 0 when the prompt-lookup
    drafter finds nothing to propose in the workload), and the greedy
    row pins the latency baseline the fused in-program sampler must
    not regress."""
    greedy = bench_serving(f"{name}[greedy]", spec_decode=False, **kw)
    sampled = bench_serving(f"{name}[sampled]", temperature=temperature,
                            top_p=top_p, spec_decode=False, **kw)
    replay = bench_serving(f"{name}[sampled-replay]", emit=False,
                           temperature=temperature, top_p=top_p,
                           spec_decode=False, **kw)
    spec = bench_serving(f"{name}[sampled+spec]", temperature=temperature,
                         top_p=top_p, spec_decode=True, **kw)
    print(json.dumps({
        "config": name, "preset": greedy["preset"],
        "sampling": "greedy-vs-sampled-vs-sampled+spec",
        "temperature": temperature, "top_p": top_p,
        "sampled_replay_identical": sampled["_results"] == replay["_results"],
        "sampled_tokens": sampled["sampled_tokens"],
        "spec_accept_rate": spec["accept_rate"],
        "spec_tokens_per_step": spec["tokens_per_step"],
        "tokens_per_s_greedy": greedy["tokens_per_s"],
        "tokens_per_s_sampled": sampled["tokens_per_s"],
        "tokens_per_s_sampled_spec": spec["tokens_per_s"],
        "ms_per_token_greedy": greedy["ms_per_token"],
        "ms_per_token_sampled": sampled["ms_per_token"],
    }), flush=True)


def bench_serving_kvquant_compare(name, **kw):
    """Same serving drive with the int8 paged KV cache OFF then ON.
    Unlike the prefix/spec comparisons the streams are NOT bit-equal
    (int8 rounds the cache), so the row reports the greedy token match
    rate instead; the headline columns are the fixed-HBM capacity ratio
    (slots_admittable, ~2x) and the per-token cache traffic ratio."""
    off = bench_serving(f"{name}[off]", kv_quant="off", **kw)
    on = bench_serving(f"{name}[int8]", kv_quant="int8", **kw)
    tot = match = 0
    for rid, ref in off["_results"].items():
        got = on["_results"].get(rid, [])
        n = min(len(ref), len(got))
        match += sum(a == b for a, b in zip(ref[:n], got[:n]))
        tot += max(len(ref), len(got))
    print(json.dumps({
        "config": name, "preset": off["preset"],
        "kv_quant": "off-vs-int8",
        "token_match_rate": round(match / max(tot, 1), 4),
        "kv_pool_dtype_off": off["kv_pool_dtype"],
        "kv_pool_dtype_int8": on["kv_pool_dtype"],
        "kv_cache_bytes_per_token_off": off["kv_cache_bytes_per_token"],
        "kv_cache_bytes_per_token_int8": on["kv_cache_bytes_per_token"],
        "cache_bytes_ratio": round(
            off["kv_cache_bytes_per_token"]
            / max(on["kv_cache_bytes_per_token"], 1e-9), 2),
        "slots_admittable_off": off["slots_admittable"],
        "slots_admittable_int8": on["slots_admittable"],
        "capacity_ratio": round(
            on["slots_admittable"]
            / max(off["slots_admittable"], 1), 2),
        "kv_hbm_bytes_per_token_off": off["kv_hbm_bytes_per_token"],
        "kv_hbm_bytes_per_token_int8": on["kv_hbm_bytes_per_token"],
        "tokens_per_s_off": off["tokens_per_s"],
        "tokens_per_s_int8": on["tokens_per_s"],
    }), flush=True)


def bench_serving_router_compare(name, preset=None, num_requests=12,
                                 mean_gap_steps=2.0, prompt_lens=(8, 40),
                                 new_tokens=16, num_slots=2, block_size=8,
                                 num_blocks=None, prefill_chunk=16,
                                 n_replicas=3, kill_step=12, seed=0):
    """Same request set driven through ONE undisturbed ServingEngine and
    through an n_replicas ReplicaRouter fleet with one replica killed
    mid-run (injected ``router.step`` crash at a pinned visit): the row
    is the availability story — drained_requests recovered onto
    survivors, greedy-stream parity with the undisturbed run (the drain
    re-prefills prompt+partial, so tokens must be IDENTICAL), and the
    p99 TTFT delta the kill + drain costs."""
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.router import ReplicaRouter
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.telemetry import Telemetry
    from deepspeed_tpu.utils.faults import Fault, FaultInjector

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_seq = prompt_lens[1] + new_tokens + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    eng = deepspeed_tpu.init_inference(
        model=(cfg, gpt.init_params(jax.random.PRNGKey(0), cfg)),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    rng = np.random.default_rng(seed)
    arrive = np.floor(np.cumsum(
        rng.exponential(mean_gap_steps, num_requests))).astype(int)
    prompts = [rng.integers(0, cfg.vocab_size,
                            rng.integers(*prompt_lens)).astype(np.int32)
               for _ in range(num_requests)]

    def mk_reqs():
        return [ServeRequest(rid=i, prompt=prompts[i].copy(),
                             max_new_tokens=new_tokens)
                for i in range(num_requests)]

    def mk_srv(tel=None, faults=None):
        return ServingEngine(eng, num_slots=num_slots,
                             block_size=block_size, num_blocks=num_blocks,
                             prefill_chunk=prefill_chunk, spec_decode=False,
                             telemetry=tel, faults=faults)

    # warmup: compile the slot programs outside both timed drives
    mk_srv().run([ServeRequest(rid="w", prompt=prompts[0].copy(),
                               max_new_tokens=2)])

    def drive(submit, step, busy):
        t0 = time.perf_counter()
        s = nxt = 0
        reqs = mk_reqs()
        while nxt < num_requests or busy():
            while nxt < num_requests and arrive[nxt] <= s:
                submit(reqs[nxt], now=time.perf_counter())
                nxt += 1
            step(now=time.perf_counter())
            s += 1
        return time.perf_counter() - t0

    # undisturbed 1-replica baseline
    tel1 = Telemetry()
    solo = mk_srv(tel=tel1)
    wall1 = drive(solo.submit, solo.step, lambda: solo.busy)
    out1 = {r.rid: r.tokens.tolist() for r in solo.finished}
    ttft1 = solo.metrics.histogram("serving_ttft")

    # n-replica fleet, one replica crash-killed mid-run; the shared
    # Telemetry aggregates serving_ttft across replicas (get-or-create
    # registry), so the fleet percentile includes drained re-prefills
    inj = FaultInjector([Fault("router.step", "crash", step=kill_step)],
                        seed=seed)
    teln = Telemetry()
    fleet = [mk_srv(tel=teln, faults=inj) for _ in range(n_replicas)]
    router = ReplicaRouter(fleet, faults=inj, telemetry=teln)
    walln = drive(router.submit, router.step, lambda: router.busy)
    outn = {rid: np.asarray(t).tolist()
            for rid, t in router.results().items()}
    ttftn = fleet[0].metrics.histogram("serving_ttft")

    gen1 = sum(len(r.out) for r in solo.finished)
    genn = sum(len(outn[i]) - len(prompts[i]) for i in outn)
    print(json.dumps({
        "config": name, "preset": preset or "cpu-smoke",
        "router": f"1-vs-{n_replicas}(kill 1)",
        "num_requests": num_requests, "n_replicas": n_replicas,
        "replica_killed": bool(inj.fired),
        "drained_requests": router.stats["drained_requests"],
        "breaker_trips": router.stats["breaker_trips"],
        "redispatches": router.stats["redispatches"],
        "replica_health": router.health(),
        "output_identical": all(
            outn.get(i) == out1[i] for i in out1),
        "ttft_p99_ms_solo": round(ttft1.percentile(99) * 1e3, 3),
        "ttft_p99_ms_fleet": round(ttftn.percentile(99) * 1e3, 3),
        "ttft_p99_delta_ms": round(
            (ttftn.percentile(99) - ttft1.percentile(99)) * 1e3, 3),
        "tokens_per_s_solo": round(gen1 / wall1, 1),
        "tokens_per_s_fleet": round(genn / walln, 1),
    }), flush=True)


def bench_serving_lora_compare(name, preset=None, num_requests=10,
                               mean_gap_steps=2.0, prompt_lens=(6, 14),
                               new_tokens=8, num_slots=2, block_size=8,
                               num_blocks=None, prefill_chunk=16,
                               n_adapters=3, rank=4,
                               lora_pool_blocks=None, seed=0):
    """Multi-tenant LoRA serving (docs/ADAPTERS.md), three legs over
    one seeded tenant population: (a) merged-single — adapter 0 baked
    into the weights with ``merge_lora``, base-only serving (the
    pre-subsystem reference and the ms/token floor); (b)
    unmerged-single — the SAME requests through the adapter pool, whose
    greedy streams must be IDENTICAL to (a); (c) mixed — a
    Zipf-popular multi-adapter + base-only population in one engine,
    every stream checked against its own tenant's merged reference.
    The row is the bit-parity verdict, the pool's hit/load/eviction
    economics, and the ms/token price of the gathered low-rank
    matmuls."""
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.runtime.lora import (add_lora, adapter_state_dict,
                                            merge_lora)

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_seq = prompt_lens[1] + new_tokens + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # n_adapters distinct fine-tunes: add_lora's B starts at zero (a
    # zero delta would make every leg trivially identical), so each
    # tenant gets seeded noise in B — distinct, nonzero deltas
    exports = []
    merged = []
    for t in range(n_adapters):
        lp = add_lora(params, rank=rank, alpha=2.0 * rank,
                      rng=jax.random.PRNGKey(seed + 100 + t))
        nrng = np.random.default_rng(seed + 200 + t)
        blk = dict(lp["block"])
        for tgt, entry in blk.items():
            if isinstance(entry, dict) and "lora_b" in entry:
                e = dict(entry)
                e["lora_b"] = jnp.asarray(
                    nrng.standard_normal(e["lora_b"].shape) * 0.05,
                    jnp.float32)
                blk[tgt] = e
        lp = dict(lp)
        lp["block"] = blk
        exports.append(adapter_state_dict(lp))
        merged.append(merge_lora(lp))

    rng = np.random.default_rng(seed)
    arrive = np.floor(np.cumsum(
        rng.exponential(mean_gap_steps, num_requests))).astype(int)
    prompts = [rng.integers(0, cfg.vocab_size,
                            rng.integers(*prompt_lens)).astype(np.int32)
               for _ in range(num_requests)]
    # Zipf-popular tenant per request, with a base-only fraction; rid 0
    # pinned to tenant 0 so the single-adapter legs are never empty
    tenants = [0] + [
        None if rng.random() < 0.25
        else (int(rng.zipf(1.5)) - 1) % n_adapters
        for _ in range(num_requests - 1)]

    def mk_reqs(only=None):
        return [ServeRequest(
                    rid=i, prompt=prompts[i].copy(),
                    max_new_tokens=new_tokens,
                    adapter_id=(f"tenant-{tenants[i]}"
                                if tenants[i] is not None else None))
                for i in range(num_requests)
                if only is None or tenants[i] == only]

    def drive(srv, reqs, register=()):
        for aid, sd in register:
            srv.register_adapter(aid, sd)
        t0 = time.perf_counter()
        s = nxt = 0
        byrid = {r.rid: r for r in reqs}
        order = sorted(byrid)
        while nxt < len(order) or srv.busy:
            while nxt < len(order) and arrive[order[nxt]] <= s:
                srv.submit(byrid[order[nxt]], now=time.perf_counter())
                nxt += 1
            srv.step(now=time.perf_counter())
            s += 1
        wall = time.perf_counter() - t0
        gen = sum(len(r.out) for r in srv.finished)
        return ({r.rid: r.tokens.tolist() for r in srv.finished},
                round(wall / max(gen, 1) * 1e3, 3))

    def mk_srv(eng, lora=False):
        return ServingEngine(
            eng, num_slots=num_slots, block_size=block_size,
            num_blocks=num_blocks, prefill_chunk=prefill_chunk,
            spec_decode=False, lora_serve=lora,
            lora_pool_blocks=lora_pool_blocks if lora else None)

    # per-tenant merged reference engines (+ the plain base engine for
    # base-only requests); compile outside the timed legs via warmup
    eng_base = deepspeed_tpu.init_inference(model=(cfg, params),
                                            dtype=dtype)
    engs_merged = [deepspeed_tpu.init_inference(model=(cfg, m),
                                                dtype=dtype)
                   for m in merged]
    eng_lora = deepspeed_tpu.init_inference(model=(cfg, params),
                                            dtype=dtype)
    warm = [ServeRequest(rid="w", prompt=prompts[0].copy(),
                        max_new_tokens=2)]
    mk_srv(eng_base).run([ServeRequest(rid="w", prompt=prompts[0].copy(),
                                       max_new_tokens=2)])
    for e in engs_merged:
        mk_srv(e).run([ServeRequest(rid="w", prompt=prompts[0].copy(),
                                    max_new_tokens=2)])
    wsrv = mk_srv(eng_lora, lora=True)
    wsrv.register_adapter("tenant-0", exports[0])
    warm[0].adapter_id = "tenant-0"
    wsrv.run(warm)

    # reference streams: every tenant's requests through ITS merged
    # engine, base-only requests through the base engine (burst drive —
    # greedy slot streams are batching-independent by contract)
    refs = {}
    for t in range(n_adapters):
        reqs = [ServeRequest(rid=r.rid, prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens)
                for r in mk_reqs(only=t)]
        if reqs:
            refs.update(mk_srv(engs_merged[t]).run(reqs))
    base_reqs = [ServeRequest(rid=r.rid, prompt=r.prompt,
                              max_new_tokens=r.max_new_tokens)
                 for r in mk_reqs(only=None) if r.adapter_id is None]
    if base_reqs:
        refs.update(mk_srv(eng_base).run(base_reqs))
    refs = {rid: np.asarray(t).tolist() for rid, t in refs.items()}

    # leg (a): merged-single — tenant 0 baked in, base-only serving
    out_m, mspt_merged = drive(mk_srv(engs_merged[0]),
                               [ServeRequest(rid=r.rid, prompt=r.prompt,
                                             max_new_tokens=r.max_new_tokens)
                                for r in mk_reqs(only=0)])
    # leg (b): unmerged-single — same requests through the pool
    out_u, mspt_unmerged = drive(mk_srv(eng_lora, lora=True),
                                 mk_reqs(only=0),
                                 register=[("tenant-0", exports[0])])
    # leg (c): mixed-adapter batch, full population
    srv_x = mk_srv(eng_lora, lora=True)
    out_x, mspt_mixed = drive(
        srv_x, mk_reqs(),
        register=[(f"tenant-{t}", exports[t])
                  for t in range(n_adapters)])
    st = srv_x.stats
    pool = srv_x.adapters.stats()
    acq = st["adapter_hits"] + st["adapter_loads"]
    print(json.dumps({
        "config": name, "preset": preset or "cpu-smoke",
        "lora": f"merged-vs-unmerged-vs-mixed({n_adapters} adapters)",
        "num_requests": num_requests, "n_adapters": n_adapters,
        "rank": rank, "pool_blocks": pool["pool_blocks"],
        "single_adapter_identical": out_u == out_m,
        "output_identical": all(out_x.get(rid) == refs[rid]
                                for rid in refs),
        "base_only_requests": sum(1 for t in tenants if t is None),
        "adapter_hit_rate": round(st["adapter_hits"] / max(acq, 1), 3),
        "adapter_loads": st["adapter_loads"],
        "adapter_evictions": st["adapter_evictions"],
        "adapter_load_errors": st["adapter_load_errors"],
        "ms_per_token_merged_single": mspt_merged,
        "ms_per_token_unmerged_single": mspt_unmerged,
        "ms_per_token_mixed": mspt_mixed,
        "ms_per_token_delta": round(mspt_unmerged - mspt_merged, 3),
    }), flush=True)


def bench_serving_cost_attrib(name, preset=None, num_requests=10,
                              mean_gap_steps=2.0, prompt_lens=(6, 14),
                              new_tokens=8, num_slots=2, block_size=8,
                              prefill_chunk=16, n_adapters=2, rank=4,
                              seed=0):
    """Per-tenant cost attribution (telemetry/costs.py): a mixed
    base + n_adapters LoRA population through ONE engine with the cost
    accountant on, reporting each tenant's exact FLOPs/HBM-bytes/
    KV-block-seconds footprint, the per-dispatch-class totals, and the
    conservation verdict (sum of per-request footprints == the global
    counters, per class — exact integers, not approximately)."""
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.runtime.lora import add_lora, adapter_state_dict
    from deepspeed_tpu.telemetry import Telemetry
    from deepspeed_tpu.utils.jit_registry import DISPATCH_CLASSES

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_seq = prompt_lens[1] + new_tokens + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    eng = deepspeed_tpu.init_inference(model=(cfg, params), dtype=dtype)
    srv = ServingEngine(eng, num_slots=num_slots, block_size=block_size,
                        prefill_chunk=prefill_chunk, spec_decode=False,
                        lora_serve=True, telemetry=Telemetry())
    for t in range(n_adapters):
        srv.register_adapter(
            f"tenant-{t}",
            adapter_state_dict(add_lora(
                params, rank=rank, alpha=2.0 * rank,
                rng=jax.random.PRNGKey(seed + 100 + t))))

    rng = np.random.default_rng(seed)
    arrive = np.floor(np.cumsum(
        rng.exponential(mean_gap_steps, num_requests))).astype(int)
    reqs = [ServeRequest(
                rid=i,
                prompt=rng.integers(0, cfg.vocab_size,
                                    rng.integers(*prompt_lens)
                                    ).astype(np.int32),
                max_new_tokens=new_tokens,
                adapter_id=(f"tenant-{i % n_adapters}"
                            if i % 3 else None))
            for i in range(num_requests)]
    t0 = time.perf_counter()
    s = nxt = 0
    while nxt < num_requests or srv.busy:
        while nxt < num_requests and arrive[nxt] <= s:
            srv.submit(reqs[nxt], now=time.perf_counter())
            nxt += 1
        srv.step(now=time.perf_counter())
        s += 1
    wall_s = time.perf_counter() - t0

    snap = srv.costs.snapshot()
    # conservation check, same arithmetic the test suite pins: refold
    # every per-request footprint (plus the unowned system residue)
    # and compare against the accountant's per-class totals
    folded = {c: {"flops": 0, "hbm_bytes": 0, "dispatches": 0}
              for c in DISPATCH_CLASSES}
    for r in srv.finished:
        for c in DISPATCH_CLASSES:
            for k in folded[c]:
                folded[c][k] += r.cost[c][k]
    for c in DISPATCH_CLASSES:
        for k in folded[c]:
            folded[c][k] += srv.costs.system[c][k]
    conserved = all(folded[c][k] == srv.costs.totals[c][k]
                    for c in DISPATCH_CLASSES for k in folded[c])
    gen_tokens = sum(len(r.out) for r in srv.finished)
    row = {
        "config": name, "preset": preset or "cpu-smoke",
        "num_requests": num_requests, "n_adapters": n_adapters,
        "completed": srv.stats["completed"],
        "tokens_per_s": round(gen_tokens / max(wall_s, 1e-9), 1),
        "conservation_exact": bool(conserved),
        "serve_cost_flops_total": snap["flops_total"],
        "serve_cost_hbm_bytes_total": snap["hbm_bytes_total"],
        "serve_cost_kv_block_seconds": snap["block_seconds_total"],
        "cost_registry_programs": len(srv.cost_registry.entries),
        "per_class": {c: dict(srv.costs.totals[c])
                      for c in DISPATCH_CLASSES},
        "per_tenant": {
            tid: {"flops": sum(fp[c]["flops"] for c in DISPATCH_CLASSES),
                  "hbm_bytes": sum(fp[c]["hbm_bytes"]
                                   for c in DISPATCH_CLASSES),
                  "block_seconds": fp["block_seconds"]}
            for tid, fp in sorted(srv.costs.tenants.items())},
    }
    print(json.dumps(row), flush=True)
    return row


def bench_serving_autoscale_compare(name, preset=None, num_slots=2,
                                    block_size=8, num_blocks=None,
                                    prefill_chunk=16, max_replicas=3,
                                    ttft_slo=12.0, queue_high=2.0,
                                    mix="chat",
                                    phases=((6, 0.2), (60, 0.5), (30, 0.05)),
                                    seed=0):
    """The closed-loop SLO story (docs/OBSERVABILITY.md): ONE seeded
    load-gen population with a rate spike in the middle, driven in
    scheduler-STEP clock units through (a) a FIXED 1-replica fleet and
    (b) a policy fleet that starts at 1 replica with the
    :class:`SLOController` active. The fixed fleet queues through the
    spike and violates the stated p99-TTFT SLO; the controller sees the
    windowed p99 cross the budget, scales up via ``replica_factory``
    (sharing the one ``InferenceEngine`` — zero new compiled programs)
    and holds it. ``slo_attainment`` is recomputed from the per-request
    first-token timestamps; ``replicas_high_water`` and
    ``autoscale_decisions`` come from the fleet registry. The whole
    drive is deterministic under ``seed`` (step-unit clock, seeded
    arrivals, host-side controller), so the row regresses bit-for-bit."""
    from tools.load_gen import drive, make_requests
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.autoscale import SLOController
    from deepspeed_tpu.inference.router import ReplicaRouter
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.telemetry import Telemetry

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_prompt = 40
    max_seq = max_prompt + 24 + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    eng = deepspeed_tpu.init_inference(
        model=(cfg, gpt.init_params(jax.random.PRNGKey(0), cfg)),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    entries = make_requests(seed=seed, mix=mix, phases=list(phases),
                            vocab_size=cfg.vocab_size,
                            max_prompt_len=max_prompt)

    def mk_srv(tel):
        return ServingEngine(eng, num_slots=num_slots,
                             block_size=block_size, num_blocks=num_blocks,
                             prefill_chunk=prefill_chunk, spec_decode=False,
                             telemetry=tel)

    # warmup: compile the slot programs outside both drives
    mk_srv(None).run([ServeRequest(
        rid="w", prompt=np.asarray(entries[0]["prompt"], np.int32),
        max_new_tokens=2)])

    # fixed fleet: one replica, no controller — the SLO-violation shape
    tel_f = Telemetry()
    fixed = ReplicaRouter([mk_srv(tel_f)], telemetry=tel_f)
    res_f = drive(fixed, entries, mode="open", slo_ttft=ttft_slo)

    # policy fleet: same population, controller active; replicas come
    # from the factory SHARING eng, so scale-up compiles nothing
    tel_p = Telemetry()
    ctrl = SLOController(ttft_slo=ttft_slo, window=16.0, eval_every=2,
                         max_replicas=max_replicas, cooldown=4.0,
                         idle_to_retire=1e9, min_samples=3,
                         queue_high=queue_high)
    policy = ReplicaRouter([mk_srv(tel_p)],
                           replica_factory=lambda i, tag: mk_srv(tel_p),
                           telemetry=tel_p, autoscale=ctrl)
    res_p = drive(policy, entries, mode="open", slo_ttft=ttft_slo)

    snap = policy.fleet_snapshot()
    print(json.dumps({
        "config": name, "preset": preset or "cpu-smoke",
        "autoscale": f"fixed-1-vs-policy-{max_replicas}",
        "num_requests": len(entries), "mix": mix,
        "ttft_slo_steps": ttft_slo,
        "ttft_p99_fixed": round(res_f["ttft_p99"], 2),
        "ttft_p99_policy": round(res_p["ttft_p99"], 2),
        "slo_attainment_fixed": round(res_f["slo_attainment"], 3),
        "slo_attainment": round(res_p["slo_attainment"], 3),
        "slo_violated_fixed": res_f["ttft_p99"] > ttft_slo,
        "slo_holds_policy": res_p["ttft_p99"] <= ttft_slo,
        "replicas_high_water":
            1 + snap["counters"]["router_scale_ups"],
        "autoscale_decisions": snap["counters"]["autoscale_decisions"],
        "autoscale_scale_ups": snap["counters"]["autoscale_scale_ups"],
        "fleet_health": policy.health(),
        "steps_fixed": res_f["steps"], "steps_policy": res_p["steps"],
    }), flush=True)
    return res_f, res_p, policy


def bench_serving_disagg_compare(name, preset=None, num_replicas=2,
                                 num_slots=2, block_size=8,
                                 num_blocks=24, prefill_chunk=8,
                                 phases=((110, 0.27),), seed=3,
                                 max_prompt=64):
    """Disaggregated prefill/decode vs monolithic at the SAME chip
    count (docs/ROBUSTNESS.md): ONE seeded mixed rag+chat load-gen
    trace (Zipf-popular rag document prefixes) driven through (a)
    ``num_replicas`` mixed-role replicas and (b) the same replicas
    split into 1 prefill + N-1 decode roles, KV migrating between
    pools through the CRC-verified host channel. The monolithic fleet
    interleaves long rag prefills with interactive chat decodes in the
    same slots — head-of-line prefill wait and block-pressure
    preemption violate at least one per-kind p99 SLO budget
    (tools/load_gen.SLO_TARGETS); the split fleet must hold ALL of
    them, with byte-identical per-request tokens (``output_identical``
    — migration resume is exact, and every injected-fault fallback
    degrades to a cold re-prefill, never a wrong token). The disagg
    drive runs under ``CompileWatch(0)``: migration gather/scatter
    lanes are pre-warmed at router construction, so the steady state
    compiles nothing. Ambient ``DS_FAULTS`` naming the three
    ``router.migrate_*`` sites turns this row into the chaos leg:
    ``migration_fallbacks`` goes positive and every assert still
    holds."""
    from tools.load_gen import SLO_TARGETS, drive, make_requests
    from deepspeed_tpu.models import gpt
    import deepspeed_tpu
    from deepspeed_tpu.inference.router import ReplicaRouter
    from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
    from deepspeed_tpu.telemetry import Telemetry
    from deepspeed_tpu.utils.compile_guard import CompileWatch

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    max_seq = max_prompt + 16 + 8
    if preset:
        cfg = gpt.preset(preset, max_seq_len=max_seq, dtype=jnp.bfloat16,
                         use_flash_attention=on_tpu)
    else:
        cfg = gpt.GPTConfig(vocab_size=512, n_layers=4, n_heads=8,
                            d_model=256, max_seq_len=max_seq,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
    eng = deepspeed_tpu.init_inference(
        model=(cfg, gpt.init_params(jax.random.PRNGKey(0), cfg)),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    entries = make_requests(seed=seed, mix="mixed", phases=list(phases),
                            vocab_size=cfg.vocab_size,
                            max_prompt_len=max_prompt)

    def mk_srv(tel):
        return ServingEngine(eng, num_slots=num_slots,
                             block_size=block_size, num_blocks=num_blocks,
                             prefill_chunk=prefill_chunk,
                             spec_decode=False, telemetry=tel)

    # warmup: compile prefill/decode slot programs outside both drives
    mk_srv(None).run([ServeRequest(
        rid="w", prompt=np.asarray(entries[0]["prompt"], np.int32),
        max_new_tokens=2)])

    def kind_p99(res, key):
        out = {}
        for kind in ("chat", "rag"):
            vals = [r[key] for r in res["per_request"]
                    if r["kind"] == kind and r[key] is not None]
            out[kind] = (float(np.percentile(np.asarray(vals), 99))
                         if vals else 0.0)
        return out

    def slo_holds(res):
        ttft, tpot = kind_p99(res, "ttft"), kind_p99(res, "tpot")
        return all(ttft[k] <= SLO_TARGETS[k]["ttft"]
                   and tpot[k] <= SLO_TARGETS[k]["tpot"]
                   for k in ("chat", "rag"))

    # (a) monolithic: every replica mixed-role — the contention shape
    tel_m = Telemetry()
    mono = ReplicaRouter([mk_srv(tel_m) for _ in range(num_replicas)],
                         telemetry=tel_m)
    res_m = drive(mono, entries, mode="open", include_tokens=True)

    # (b) same chip count, split roles: KV migrates prefill -> decode.
    # Router construction pre-warms the migration gather/scatter lanes,
    # so the watched drive must compile NOTHING.
    tel_d = Telemetry()
    roles = ["prefill"] + ["decode"] * (num_replicas - 1)
    disagg = ReplicaRouter([mk_srv(tel_d) for _ in range(num_replicas)],
                           roles=roles, telemetry=tel_d)
    watch = CompileWatch(max_compiles=0, label="disagg steady state")
    with watch:
        res_d = drive(disagg, entries, mode="open", include_tokens=True)

    toks_m = {r["rid"]: r["tokens"] for r in res_m["per_request"]}
    toks_d = {r["rid"]: r["tokens"] for r in res_d["per_request"]}
    identical = toks_m == toks_d

    ttft_m, tpot_m = kind_p99(res_m, "ttft"), kind_p99(res_m, "tpot")
    ttft_d, tpot_d = kind_p99(res_d, "ttft"), kind_p99(res_d, "tpot")
    snap = disagg.fleet_snapshot()
    row = {
        "config": name, "preset": preset or "cpu-smoke",
        "disagg": f"{num_replicas}-mixed-vs-1prefill+"
                  f"{num_replicas - 1}decode",
        "num_requests": len(entries),
        "slo_targets": {k: SLO_TARGETS[k] for k in ("chat", "rag")},
        "ttft_p99_mono": {k: round(v, 2) for k, v in ttft_m.items()},
        "tpot_p99_mono": {k: round(v, 2) for k, v in tpot_m.items()},
        "ttft_p99_disagg": {k: round(v, 2) for k, v in ttft_d.items()},
        "tpot_p99_disagg": {k: round(v, 2) for k, v in tpot_d.items()},
        "slo_violated_mono": not slo_holds(res_m),
        "slo_holds_disagg": slo_holds(res_d),
        "migrations": snap["counters"]["router_migrations"],
        "migration_fallbacks":
            snap["counters"]["router_migration_fallbacks"],
        "output_identical": identical,
        "steady_state_compiles": watch.compiles,
        "steps_mono": res_m["steps"], "steps_disagg": res_d["steps"],
    }
    print(json.dumps(row), flush=True)
    return row, res_m, res_d, disagg


SERVE_CONFIGS = [
    # CPU-verifiable smoke: staggered Poisson arrivals must batch
    # (mean_occupancy > 1) and the paged footprint must undercut the
    # static num_slots x S_max reservation
    ("serve-smoke", dict(num_requests=12, mean_gap_steps=2.0,
                         prompt_lens=(8, 40), new_tokens=16, num_slots=4,
                         block_size=8, prefill_chunk=16)),
    # on-chip rows: bf16 and weight-only int8 through the same scheduler
    # (int8-fused additionally routes dense through ops/int8_matmul)
    ("serve-gpt2-medium", dict(preset="gpt2-medium", num_requests=32,
                               mean_gap_steps=1.5, prompt_lens=(64, 384),
                               new_tokens=64, num_slots=8,
                               block_size=16, prefill_chunk=128)),
    ("serve-gpt2-medium-int8-fused", dict(
        preset="gpt2-medium", num_requests=32, mean_gap_steps=1.5,
        prompt_lens=(64, 384), new_tokens=64, num_slots=8,
        block_size=16, prefill_chunk=128, int8=True, int8_fused=True)),
]

# gather-vs-pallas comparison drives (one config, both impls): the
# on-chip row is the kernel's headline; the smoke row runs the pallas
# kernel in INTERPRET mode on CPU, so its wall-clock is meaningless but
# the identical-stream and traffic-accounting columns still verify
SERVE_COMPARE_CONFIGS = [
    ("serve-impl-smoke", dict(num_requests=6, mean_gap_steps=2.0,
                              prompt_lens=(8, 24), new_tokens=8,
                              num_slots=2, block_size=8,
                              prefill_chunk=16)),
    ("serve-impl-gpt2-medium", dict(preset="gpt2-medium", num_requests=32,
                                    mean_gap_steps=1.5,
                                    prompt_lens=(64, 384), new_tokens=64,
                                    num_slots=8, block_size=16,
                                    prefill_chunk=128)),
    # shared-system-prompt workload, DS_PREFIX_CACHE on vs off: every
    # request opens with the same shared_prefix_len tokens, so the warm
    # path must report prefix_hit_rate > 0 and fewer prefill chunks
    # while streams stay identical
    ("serve-prefix-smoke", dict(mode="prefix", num_requests=8,
                                mean_gap_steps=2.0, prompt_lens=(4, 12),
                                new_tokens=8, num_slots=2, block_size=8,
                                prefill_chunk=16, shared_prefix_len=24)),
    ("serve-prefix-gpt2-medium", dict(
        mode="prefix", preset="gpt2-medium", num_requests=32,
        mean_gap_steps=1.5, prompt_lens=(16, 128), new_tokens=64,
        num_slots=8, block_size=16, prefill_chunk=128,
        shared_prefix_len=256)),
    # host-DRAM KV tier at a CONSTRAINED device pool: three prompt
    # families rotate through two visits each, so every family's chain
    # goes cold between visits — the off row loses those chains to
    # device eviction, the on row must report host_spills > 0,
    # host_restores > 0 and a higher prefix_hit_rate at the same
    # num_blocks, with identical greedy streams
    ("serve-hosttier-smoke", dict(mode="hosttier", num_requests=12,
                                  mean_gap_steps=2.0, prompt_lens=(4, 12),
                                  new_tokens=8, num_slots=2, block_size=8,
                                  prefill_chunk=16, shared_prefix_len=24,
                                  prefix_families=3, num_blocks=14,
                                  spill_watermark=12)),
    ("serve-hosttier-gpt2-medium", dict(
        mode="hosttier", preset="gpt2-medium", num_requests=24,
        mean_gap_steps=1.5, prompt_lens=(16, 96), new_tokens=32,
        num_slots=4, block_size=16, prefill_chunk=64,
        shared_prefix_len=192, prefix_families=3, num_blocks=88,
        spill_watermark=32)),
    # speculative decoding on vs off over a self-similar greedy workload
    # (tiny-model greedy loops repeat, exactly what the prompt-lookup
    # drafter exploits): streams must be identical and the on row must
    # report accept_rate > 0 / tokens_per_step > 1.0
    ("serve-spec-smoke", dict(mode="spec", num_requests=8,
                              mean_gap_steps=2.0, prompt_lens=(6, 20),
                              new_tokens=16, num_slots=2, block_size=8,
                              prefill_chunk=16)),
    ("serve-spec-gpt2-medium", dict(
        mode="spec", preset="gpt2-medium", num_requests=32,
        mean_gap_steps=1.5, prompt_lens=(64, 384), new_tokens=64,
        num_slots=8, block_size=16, prefill_chunk=128)),
    # int8 paged KV cache on vs off: the off row must admit num_slots
    # at its own budget, the int8 row ~2x that (capacity_ratio >= 1.8
    # on bf16 pools; larger on the fp32 CPU smoke), with a high but not
    # bit-exact token_match_rate — the rounding tolerance is the price
    ("serve-kvquant-smoke", dict(mode="kvquant", num_requests=8,
                                 mean_gap_steps=2.0, prompt_lens=(8, 24),
                                 new_tokens=12, num_slots=2, block_size=8,
                                 prefill_chunk=16)),
    ("serve-kvquant-gpt2-medium", dict(
        mode="kvquant", preset="gpt2-medium", num_requests=32,
        mean_gap_steps=1.5, prompt_lens=(64, 384), new_tokens=64,
        num_slots=8, block_size=16, prefill_chunk=128)),
    # per-request sampling: greedy vs sampled vs sampled+spec over one
    # drive — the sampled row must replay bit-identically under its
    # fixed per-request seeds, and the sampled-spec row must keep a
    # nonzero accept_rate through the rejection-sampling verify
    ("serve-sampling-smoke", dict(mode="sampling", num_requests=8,
                                  mean_gap_steps=2.0, prompt_lens=(6, 20),
                                  new_tokens=12, num_slots=2, block_size=8,
                                  prefill_chunk=16)),
    ("serve-sampling-gpt2-medium", dict(
        mode="sampling", preset="gpt2-medium", num_requests=32,
        mean_gap_steps=1.5, prompt_lens=(64, 384), new_tokens=64,
        num_slots=8, block_size=16, prefill_chunk=128)),
    # fused multi-step decode horizons N=1 vs 4 vs 8: streams must be
    # identical at every N while host_ms_per_token falls — the host
    # scheduler loop runs once per horizon instead of once per token
    # (docs/MULTISTEP.md; chip-queue entry in ROADMAP for on-chip rows).
    # burst arrivals (gap 0) keep the slots saturated at every N: a
    # Poisson gap in scheduler-step units would make the faster-per-step
    # N=8 run sit through idle arrival-wait steps, billing host time
    # against zero tokens and muddying the amortization column
    # repeats=3/min-of-k: the n4→n8 host delta is a few µs/token on
    # CPU, inside one trial's OS jitter
    ("serve-horizon-smoke", dict(mode="horizon", num_requests=8,
                                 mean_gap_steps=0.0, prompt_lens=(6, 20),
                                 new_tokens=24, num_slots=2, block_size=8,
                                 prefill_chunk=16, repeats=3)),
    ("serve-horizon-gpt2-medium", dict(
        mode="horizon", preset="gpt2-medium", num_requests=32,
        mean_gap_steps=1.5, prompt_lens=(64, 384), new_tokens=64,
        num_slots=8, block_size=16, prefill_chunk=128)),
    # replica-fleet router availability: the same requests through one
    # undisturbed engine vs a 3-replica fleet with one replica crash-
    # killed mid-run — drained work must land on survivors with
    # identical greedy streams; ttft_p99_delta_ms is the drain's cost
    ("serve-router-smoke", dict(mode="router", num_requests=10,
                                mean_gap_steps=2.0, prompt_lens=(8, 24),
                                new_tokens=12, num_slots=2, block_size=8,
                                prefill_chunk=16, kill_step=12)),
    ("serve-router-gpt2-medium", dict(
        mode="router", preset="gpt2-medium", num_requests=24,
        mean_gap_steps=1.5, prompt_lens=(64, 256), new_tokens=48,
        num_slots=4, block_size=16, prefill_chunk=128, kill_step=40)),
    # SLO autoscaling: one seeded spiky load-gen population through a
    # fixed 1-replica fleet vs a policy fleet with the SLOController
    # active — the fixed fleet must violate the stated p99-TTFT SLO
    # through the spike and the policy fleet must hold it by scaling
    # up (replicas_high_water / autoscale_decisions registry-sourced)
    ("serve-autoscale-smoke", dict(mode="autoscale", num_slots=2,
                                   block_size=8, prefill_chunk=16,
                                   max_replicas=3, ttft_slo=12.0,
                                   phases=((6, 0.2), (60, 0.5),
                                           (30, 0.05)))),
    ("serve-autoscale-gpt2-medium", dict(
        mode="autoscale", preset="gpt2-medium", num_slots=4,
        block_size=16, prefill_chunk=64, max_replicas=3, ttft_slo=12.0,
        phases=((6, 0.2), (60, 0.5), (30, 0.05)))),
    # disaggregated prefill/decode at the same chip count: the mixed
    # rag+chat trace must violate at least one per-kind p99 SLO budget
    # on the monolithic fleet while the 1-prefill+1-decode split holds
    # ALL of them, with byte-identical tokens (migration resume is
    # exact) and zero compiles in the watched steady state
    ("serve-disagg-smoke", dict(mode="disagg", num_replicas=2,
                                num_slots=2, block_size=8,
                                num_blocks=24, prefill_chunk=8,
                                phases=((110, 0.27),), seed=3,
                                max_prompt=64)),
    ("serve-disagg-gpt2-medium", dict(
        mode="disagg", preset="gpt2-medium", num_replicas=2,
        num_slots=2, block_size=8, num_blocks=24, prefill_chunk=8,
        phases=((110, 0.27),), seed=3, max_prompt=64)),
    # multi-tenant LoRA serving: merged-single vs unmerged-single must
    # stream identically (the bit-parity contract), and the mixed
    # Zipf-tenant drive must match per-tenant merged references while
    # the constrained pool (smoke: 3 blocks < 4 tenants, pinned slots
    # can never exhaust it) reports loads/hits/evictions; the
    # ms_per_token delta is the gathered low-rank matmuls' price
    ("serve-lora-smoke", dict(mode="lora", num_requests=10,
                              mean_gap_steps=2.0, prompt_lens=(6, 14),
                              new_tokens=8, num_slots=2, block_size=8,
                              prefill_chunk=16, n_adapters=4, rank=4,
                              lora_pool_blocks=3)),
    ("serve-lora-gpt2-medium", dict(
        mode="lora", preset="gpt2-medium", num_requests=24,
        mean_gap_steps=1.5, prompt_lens=(16, 96), new_tokens=32,
        num_slots=4, block_size=16, prefill_chunk=64, n_adapters=4,
        rank=8)),
    # per-tenant cost attribution: a mixed base+LoRA population with
    # the cost accountant on — the row is each tenant's exact
    # FLOPs/HBM/block-seconds footprint and the conservation verdict
    # (sum of per-request footprints == global counters, per class)
    ("serve-cost-attrib-smoke", dict(mode="cost_attrib",
                                     num_requests=10,
                                     mean_gap_steps=2.0,
                                     prompt_lens=(6, 14), new_tokens=8,
                                     num_slots=2, block_size=8,
                                     prefill_chunk=16, n_adapters=2)),
    ("serve-cost-attrib-gpt2-medium", dict(
        mode="cost_attrib", preset="gpt2-medium", num_requests=24,
        mean_gap_steps=1.5, prompt_lens=(16, 96), new_tokens=32,
        num_slots=4, block_size=16, prefill_chunk=64, n_adapters=3)),
]


def _backend_probe(timeout=240):
    """Probe the accelerator backend in a SUBPROCESS and say WHY it
    failed: a wedged TPU tunnel hangs jax.devices() forever (observed
    on this rig — bench.py grew the same guard first), and a hang
    inside the driver's bench run would record nothing at all. Returns
    ``(ok, reason)``; reason is None on success."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return True, None    # a local CPU backend cannot be unreachable
    import subprocess
    probe = ("import sys; sys.path.insert(0, '.')\n"
             "from deepspeed_tpu.utils import honor_platform_request\n"
             "honor_platform_request()\n"
             "import jax; print(jax.devices())\n")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, timeout=timeout)
        if r.returncode == 0:
            return True, None
        tail = r.stderr.decode("utf-8", "replace").strip()[-200:]
        return False, f"probe exited {r.returncode}: {tail}"
    except subprocess.TimeoutExpired:
        return False, f"probe hung past {timeout}s (wedged tunnel?)"
    except Exception as e:                       # noqa: BLE001
        return False, f"probe spawn failed: {repr(e)[:200]}"


def _classify_probe_failure(reason):
    """Bucket a probe-failure reason string into a stable machine key,
    so a dashboard can aggregate outages by CLASS ("timeout" = wedged
    tunnel, "no_device" = backend up but empty, "import_error" = broken
    deploy) without regexing free-text stderr tails. The free-text
    ``reason`` still rides alongside for humans."""
    if reason is None:
        return None
    low = reason.lower()
    if "hung past" in low or "timeout" in low:
        return "timeout"
    if "spawn failed" in low:
        return "spawn_error"
    if "importerror" in low or "modulenotfounderror" in low:
        return "import_error"
    if ("no devices" in low or "unable to initialize backend" in low
            or "failed to connect" in low):
        return "no_device"
    return "other"


def _wait_for_backend():
    """Bounded recovery loop with exponential backoff: a transient
    tunnel wedge must not forfeit the whole bench round, but an
    unreachable backend must not hang it forever either. Total budget
    via ``BENCH_RECOVERY_MINUTES`` (default 25, 0 = single probe).
    Returns ``(ok, attempts, last_reason)``."""
    budget_s = float(os.environ.get("BENCH_RECOVERY_MINUTES", "25")) * 60
    deadline = time.time() + budget_s
    delay = 60
    attempt = 0
    while True:
        attempt += 1
        ok, reason = _backend_probe()
        if ok:
            return True, attempt, None
        if time.time() + delay >= deadline:
            print(f"infer_bench: backend unreachable after {attempt} "
                  f"probes", file=sys.stderr)
            return False, attempt, reason
        print(f"infer_bench: backend probe {attempt} failed "
              f"({reason}), retrying in {delay}s", file=sys.stderr)
        time.sleep(delay)
        delay = min(delay * 2, 480)


def main():
    from deepspeed_tpu.utils.hbm import MemoryGuardError
    ok, attempts, reason = _wait_for_backend()
    if not ok:
        # structured outage row: a consumer must be able to tell
        # "backend gone" from "bench crashed" without parsing stderr
        print(json.dumps({"config": "backend-probe", "probe_fail": True,
                          "status": "error:backend_unreachable",
                          "reason": reason,
                          "reason_kind": _classify_probe_failure(reason),
                          "attempts": attempts}),
              flush=True)
        return
    for name, kw in CONFIGS:
        try:
            bench_config(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)
    for name, kw in SPEC_CONFIGS:
        try:
            bench_speculative(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)
    for name, kw in SERVE_CONFIGS:
        try:
            bench_serving(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)
    for name, kw in SERVE_COMPARE_CONFIGS:
        kw = dict(kw)
        mode = kw.pop("mode", "impl")
        compare = {"prefix": bench_serving_prefix_compare,
                   "hosttier": bench_serving_hosttier_compare,
                   "spec": bench_serving_spec_compare,
                   "kvquant": bench_serving_kvquant_compare,
                   "router": bench_serving_router_compare,
                   "sampling": bench_serving_sampling_compare,
                   "autoscale": bench_serving_autoscale_compare,
                   "disagg": bench_serving_disagg_compare,
                   "lora": bench_serving_lora_compare,
                   "horizon": bench_serving_horizon_compare,
                   "cost_attrib": bench_serving_cost_attrib,
                   }.get(mode, bench_serving_impl_compare)
        try:
            compare(name, **kw)
        except MemoryGuardError as e:
            print(json.dumps({"config": name, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
        except Exception as e:
            print(json.dumps({"config": name, "error": repr(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
