"""Unattended headline autotuner — subprocess experiments over the
(micro-batch x remat policy x flash tiles x zero stage) space.

The reference's Autotuner schedules each experiment as a separate job
through a ResourceManager and prunes the space with a memory model
(ref: deepspeed/autotuning/autotuner.py:396 tune, scheduler.py:35
ResourceManager, :183 parse_results). This tool is that loop pointed at
the bench headline: every candidate passes the analytic HBM guard
BEFORE any backend contact (borderline compiles wedge this rig's remote
compile service — PERF.md incident log), then runs in its OWN process
with a wall-clock timeout via ``SubprocessRunner`` (a hang or OOM costs
one experiment, not the sweep), scored by the same ``bench.run_config``
path the driver bench uses, with the ridge cost model ordering the
remaining candidates.

Each finished experiment prints a headline_probe-format JSON line, so
``pick_headline`` can weigh autotuner results against the hand-picked
probe variants with the same incumbent/margin logic.

Usage:
  python tools/autotune_headline.py [--trials N] [--timeout S]
  python tools/autotune_headline.py --rehearse [--out-dir D]   # CPU, tiny
"""

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, ".")

from deepspeed_tpu.autotuning.scheduler import (  # noqa: E402
    Experiment, ResourceManager, SubprocessRunner)
from deepspeed_tpu.autotuning.tuner import ModelBasedTuner  # noqa: E402
from tools.headline_probe import CODE, _v, guard_variant  # noqa: E402

BEST_OUT = "AUTOTUNE_BEST.json"


def chip_space():
    """The headline family: gpt2-1.5b @ seq1024, bf16 memory_efficient,
    ZeRO-3 — micro-batch x remat policy x fwd/bwd flash tiles. ~60
    candidates before the HBM guard prunes."""
    out = {}
    for batch, pol, fb, bwd in itertools.product(
            (12, 16, 18, 20, 22),
            ("full", "offload_flash", "flash_only", "selective"),
            (1024, 512),
            (None, 512)):
        name = f"at-b{batch}-{pol}-fb{fb}-bwd{bwd or 'fwd'}"
        out[name] = _v(batch=batch, pol=pol, fb=fb, bwdq=bwd, bwdkv=bwd)
    return out


def rehearse_space():
    """CPU-backend rehearsal: tiny model, same loop mechanics. The knob
    that genuinely moves tiny-CPU throughput is the micro-batch, so the
    tuned artifact is checkable (bigger batch must win)."""
    out = {}
    for batch, remat in itertools.product((4, 8, 16), (False, True)):
        name = f"at-b{batch}-remat{int(remat)}"
        out[name] = _v(preset="llama-tiny", batch=batch, remat=remat,
                       pol="selective", lc=0, stage=1, me=False,
                       seq=32, steps=2, on_tpu=False)
    return out


def feature_view(spec):
    """Numeric feature dict for the cost model: one-hot the remat policy
    (strings featurize to 0 in dict_to_feature)."""
    d = {k: v for k, v in spec.items() if not isinstance(v, str)}
    d[f"pol_{spec['pol']}"] = True
    return d


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--early-stop", type=int, default=5)
    ap.add_argument("--timeout", type=float, default=1500.0)
    ap.add_argument("--rehearse", action="store_true",
                    help="CPU-backend tiny-model run of the whole loop")
    ap.add_argument("--out-dir", default=".",
                    help="where AUTOTUNE_BEST.json + records land")
    args = ap.parse_args()

    space = rehearse_space() if args.rehearse else chip_space()
    specs = {}
    exps = []
    for name, spec in space.items():
        if not args.rehearse:
            ok, msg = guard_variant(name, spec)
            if not ok:
                print(json.dumps({"variant": name, "skipped": "memory guard",
                                  "why": msg}), flush=True)
                continue
        specs[name] = spec
        exps.append(Experiment(name, feature_view(spec)))
    if not exps:
        print(json.dumps({"autotune": "no admissible candidates"}))
        return

    # features -> name via content (the feature view is unique per
    # candidate); injecting the name INTO ds_config would hand the cost
    # model a pure-noise hashed-string regressor
    by_feature = {json.dumps(e.ds_config, sort_keys=True, default=str):
                  e.name for e in exps}
    assert len(by_feature) == len(exps), "feature views must be unique"

    def cmd_builder(feat):
        name = by_feature[json.dumps(feat, sort_keys=True, default=str)]
        return [sys.executable, "-c",
                CODE.format(spec=specs[name], name=name)]

    def parse(stdout):
        for line in reversed(stdout.splitlines()):
            line = line.strip()
            if line.startswith("{") and '"tokens_per_s"' in line:
                rec = json.loads(line)
                print(line, flush=True)      # probe-format, for pick_headline
                return float(rec["tokens_per_s"])
        raise ValueError("no probe result line in experiment output")

    runner = SubprocessRunner(cmd_builder=cmd_builder, parse=parse,
                              timeout_s=args.timeout)
    rm = ResourceManager(runner, results_dir=os.path.join(
        args.out_dir, "autotuning_results", "headline"))
    tuner = ModelBasedTuner(exps, rm, warmup=3)
    n = tuner.tune(sample_size=1, n_trials=args.trials,
                   early_stopping=args.early_stop)

    best = rm.best()
    summary = {"autotune": "done", "ran": n,
               "failed": sum(1 for e in rm.finished_experiments if e.error),
               "errors": {e.name: e.error for e in rm.finished_experiments
                          if e.error}}
    if best is not None:
        spec = specs[best.name]
        artifact = {"chosen_from": best.name, "spec": spec,
                    "tokens_per_s": best.metric_val,
                    "batch": spec["batch"],
                    "remat_pol": spec["pol"] if spec["remat"] else "none",
                    "loss_chunk": spec["lc"], "flash_block": spec["fb"],
                    "flash_block_kv": spec["fbkv"],
                    "bwd_block_q": spec["bwdq"],
                    "bwd_block_kv": spec["bwdkv"],
                    "probe_tokens_per_s": best.metric_val}
        with open(os.path.join(args.out_dir, BEST_OUT), "w") as f:
            json.dump(artifact, f, indent=1)
        summary["best"] = best.name
        summary["tokens_per_s"] = best.metric_val
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
