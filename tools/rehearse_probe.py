"""Rehearsal stand-in for headline_probe — CPU backend only.

The unattended recovery cycle (rig_watch -> chip_queue -> pick_headline
--apply) has exactly one shot at the real rig per round; a bug anywhere
in that chain silently costs the round its bench (VERDICT r4 weak #3).
This probe lets the WHOLE chain run for real against the CPU backend:
it measures a real tiny training config through ``bench.run_config``
(same engine path the genuine probes use), then emits two probe-format
result lines — the incumbent headline variant and a faster challenger —
so pick_headline's flip path executes end to end.

Safety: the emitted lines carry the gpt2-1.5b preset label the decision
logic keys on but REHEARSAL numbers, so this tool refuses to run unless
DS_REHEARSAL=1 and refuses outright on a TPU backend. It is excluded
from chip_queue's default drain (DEFAULT_ITEMS).

Reference analog: the reference CI rehearses its perf harness on tiny
fixtures before trusting it on real runs (ref: tests/model/run_sanity_check.py:8).
"""

import json
import os
import sys

sys.path.insert(0, ".")


def main():
    if os.environ.get("DS_REHEARSAL") != "1":
        print(json.dumps({"variant": None,
                          "refused": "rehearsal probe requires DS_REHEARSAL=1"}))
        sys.exit(3)

    from deepspeed_tpu.utils import honor_platform_request
    honor_platform_request()
    import jax
    plat = jax.devices()[0].platform
    if plat != "cpu":
        print(json.dumps({"variant": None,
                          "refused": f"rehearsal probe only runs on the CPU "
                                     f"backend, got {plat!r}"}))
        sys.exit(3)

    from bench import run_config

    # a real (tiny) measurement through the same engine path as the
    # genuine probes — proves the bench plumbing executes, not just the
    # orchestration around it
    # batch 8 divides the virtual 8-device CPU mesh the tests run under
    dt, tps, mfu = run_config("llama-tiny", batch=8, seq=32, steps=2,
                              ds_overrides={}, on_tpu=False, remat=False)

    base = dict(preset="gpt2-1.5b", batch=16, remat="full", loss_chunk=2048,
                bwd_blocks=[None, None], fwd_blocks=[1024, 1024],
                step_ms=round(dt * 1e3, 1), mfu=round(mfu, 4),
                rehearsal=True)
    # incumbent, then a challenger above pick_headline's flip margin:
    # the rehearsal exercises the consequential (write) path
    print(json.dumps({**base, "variant": "b16-full-ce",
                      "tokens_per_s": round(tps, 1)}), flush=True)
    print(json.dumps({**base, "variant": "b16-offloadflash-ce",
                      "tokens_per_s": round(tps * 1.08, 1)}), flush=True)


if __name__ == "__main__":
    main()
