"""Loopback validation of the offload step's 3-stage overlap — no TPU
tunnel required.

PERF.md's offload ratio on the tunnel rig (67.7x) measures the tunnel,
not the design; the ~1.3-1.4x claim for a real PCIe link was computed,
never enforced (VERDICT r2 weak #5). This tool closes that gap by
emulating a PCIe-class link around the REAL ``HostOffloadOptimizer.step``
schedule (no reimplementation):

- stage-1 ``d2h_enqueue`` probes timestamp each transfer's launch and
  assign it a FIFO ordinal (a DMA queue serializes);
- the stage-2 materialization seam (``_read_shard``) blocks until
  ``t0 + (ordinal+1) * bytes/BW`` — the completion semantics of an
  async DMA behind a serialized link;
- the measured wall time is compared against the ideal two-stage
  pipeline bound (simulated with the bare run's per-shard Adam times)
  and the no-overlap serial model.

Prints one JSON line per link speed:
  efficiency   = T_ideal_pipeline / T_measured  (1.0 = perfect overlap)
  vs_serial    = T_measured / T_serial_model    (<1.0 = overlap wins)
Reference budget: overlapped offload step <= 1.5x the fused step
(ref: runtime/swap_tensor/pipelined_optimizer_swapper.py:60).

Usage: python tools/offload_loopback.py [bw_gbps ...]   (default 1 4)
"""

import json
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request  # noqa: E402

honor_platform_request()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from deepspeed_tpu.runtime.zero import offload as off  # noqa: E402


def build(n_leaves: int, elems: int):
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("data",))
    shard = NamedSharding(mesh, P(None))
    rng = np.random.default_rng(0)
    params = {f"w{i}": rng.standard_normal(elems).astype(np.float32)
              for i in range(n_leaves)}
    shardings = {k: shard for k in params}
    opt = off.HostOffloadOptimizer(params, lr_schedule=lambda s: 1e-3,
                                   shardings=shardings)
    grads = {k: jax.device_put(
        rng.standard_normal(elems).astype(np.float32), shard)
        for k in params}
    return opt, grads


def timed_step(opt, grads, read_seam=None):
    import threading
    main = threading.main_thread()
    events = []
    # main-thread filter: when run inside the test suite, a prior
    # engine's DPU background thread may still fire the global probe
    off._pipeline_probe = (
        lambda ev, i, k: events.append((ev, i, k, time.perf_counter()))
        if threading.current_thread() is main else None)
    off._read_shard = read_seam
    try:
        t0 = time.perf_counter()
        opt.step(grads)
        wall = time.perf_counter() - t0
    finally:
        off._pipeline_probe = None
        off._read_shard = None
    return wall, events


def adam_durations(events):
    """Per-shard Adam time from consecutive adam_done stamps in a bare
    (no-link) run — stage 2 is back-to-back there, so gaps ~= durations."""
    stamps = [t for ev, _, _, t in events if ev == "adam_done"]
    d2h_end = max(t for ev, _, _, t in events if ev == "d2h_enqueue")
    durs = [stamps[0] - d2h_end]
    durs += [b - a for a, b in zip(stamps, stamps[1:])]
    return durs


def ideal_pipeline(t_x: float, adam: list) -> float:
    """Two-stage FIFO pipeline bound: transfer k completes at (k+1)*t_x,
    Adam k starts at max(avail_k, adam_end_{k-1}); +t_x tail for the last
    h2d riding the same link."""
    end = 0.0
    for k, a in enumerate(adam):
        end = max((k + 1) * t_x, end) + a
    return end + t_x


def run(bw_gbps: float, n_leaves: int = 10, elems: int = 8_000_000):
    opt, grads = build(n_leaves, elems)
    opt.step(grads)                      # warmup: optimizer state init
    bare_wall, bare_ev = timed_step(opt, grads)
    adam = adam_durations(bare_ev)

    bytes_per = elems * 4
    t_x = bytes_per / (bw_gbps * 1e9)

    enq = {}

    def read_seam(i, k, raw):
        # FIFO-serialized DMA completion: ordinal assigned at enqueue.
        # Unknown keys (a foreign engine's background step) pass through.
        tgt = enq.get((i, k))
        if tgt is None:
            return raw
        now = time.perf_counter()
        if tgt > now:
            time.sleep(tgt - now)
        return raw

    t0_holder = {}
    # re-timestamp enqueues with FIFO ordinals inside the probe
    events = []

    import threading
    main = threading.main_thread()

    def probe_full(ev, i, k):
        if threading.current_thread() is not main:
            return
        now = time.perf_counter()
        events.append((ev, i, k, now))
        if ev == "d2h_enqueue":
            t0 = t0_holder.setdefault("t0", now)
            enq[(i, k)] = t0 + (len(enq) + 1) * t_x

    off._pipeline_probe = probe_full
    off._read_shard = read_seam
    try:
        t_start = time.perf_counter()
        opt.step(grads)
        wall = time.perf_counter() - t_start
    finally:
        off._pipeline_probe = None
        off._read_shard = None

    ideal = ideal_pipeline(t_x, adam)
    serial = n_leaves * t_x + sum(adam) + t_x    # no-overlap model
    print(json.dumps({
        "metric": "offload_pipeline_efficiency",
        "link_gbps": bw_gbps,
        "n_shards": n_leaves,
        "shard_mb": round(bytes_per / 1e6, 1),
        "t_transfer_ms": round(t_x * 1e3, 1),
        "t_adam_total_ms": round(sum(adam) * 1e3, 1),
        "measured_ms": round(wall * 1e3, 1),
        "ideal_pipeline_ms": round(ideal * 1e3, 1),
        "serial_model_ms": round(serial * 1e3, 1),
        "efficiency": round(ideal / wall, 3),
        "vs_serial": round(wall / serial, 3),
        "bare_step_ms": round(bare_wall * 1e3, 1),
    }), flush=True)
    return ideal / wall, wall / serial


def main():
    speeds = [float(a) for a in sys.argv[1:]] or [1.0, 4.0]
    for bw in speeds:
        run(bw)


if __name__ == "__main__":
    main()
