"""Pick the headline config from probe results and write BENCH_HEADLINE.json.

Parses headline_probe JSON lines ({"variant": ..., "preset": ...,
"tokens_per_s": ...}) out of a log (chip_queue/rig_watch output), keeps
the gpt2-1.5b family, and — if the best variant beats the incumbent
default (b16-full-ce) by more than a jitter margin — writes the
repo-root BENCH_HEADLINE.json that bench.py's _headline_overrides
consumes. Conservative by construction: no parsable results, no
incumbent measurement, or a within-margin winner all leave the override
absent/unchanged so the established config publishes.

Usage: python tools/pick_headline.py LOGFILE [--margin 0.01] [--apply]
Prints one decision JSON line; only --apply writes the file.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "BENCH_HEADLINE.json")
INCUMBENT = "b16-full-ce"


def parse_results(path, allow_rehearsal=False):
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not (line.startswith("{") and '"variant"' in line):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("preset") != "gpt2-1.5b":
                continue
            # rehearsal lines carry the headline preset label but FAKE
            # numbers (tools/rehearse_probe.py); they may only influence
            # a decision explicitly redirected away from the real
            # BENCH_HEADLINE.json (--out)
            if rec.get("rehearsal") and not allow_rehearsal:
                continue
            if not rec.get("tokens_per_s"):
                continue
            out[rec["variant"]] = rec          # later lines win
    return out


def overrides_for(rec):
    """Map a probe result line to bench.py's BENCH_HEADLINE.json keys."""
    ov = {"batch": rec["batch"],
          "remat_pol": rec["remat"] if rec["remat"] != "none" else "full",
          "loss_chunk": rec["loss_chunk"],
          "flash_block": rec["fwd_blocks"][0],
          "flash_block_kv": (rec["fwd_blocks"][1]
                             if rec["fwd_blocks"][1] != rec["fwd_blocks"][0]
                             else None),
          "bwd_block_q": rec["bwd_blocks"][0],
          "bwd_block_kv": rec["bwd_blocks"][1],
          "chosen_from": rec["variant"],
          "probe_tokens_per_s": rec["tokens_per_s"],
          "probe_mfu": rec["mfu"]}
    return ov


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("--margin", type=float, default=0.01,
                    help="fractional tokens/s gain required to flip")
    ap.add_argument("--apply", action="store_true")
    ap.add_argument("--out", default=None,
                    help="write target (default repo BENCH_HEADLINE.json; "
                         "the recovery rehearsal points this at a tmp path)")
    args = ap.parse_args()
    global OUT
    if args.out:
        OUT = args.out

    res = parse_results(args.log, allow_rehearsal=args.out is not None)
    if not res:
        print(json.dumps({"decision": "no results parsed"}))
        return
    best = max(res.values(), key=lambda r: r["tokens_per_s"])
    inc = res.get(INCUMBENT)
    if best["variant"] == INCUMBENT or inc is None:
        # nothing beats (or nothing measured against) the incumbent —
        # leave/remove the override so the default publishes
        if args.apply and os.path.exists(OUT):
            os.remove(OUT)
        print(json.dumps({"decision": "keep incumbent",
                          "best": best["variant"],
                          "tokens_per_s": best["tokens_per_s"],
                          "incumbent_measured": inc is not None}))
        return
    gain = best["tokens_per_s"] / inc["tokens_per_s"] - 1.0
    if gain <= args.margin:
        if args.apply and os.path.exists(OUT):
            os.remove(OUT)
        print(json.dumps({"decision": "within margin, keep incumbent",
                          "best": best["variant"],
                          "gain": round(gain, 4)}))
        return
    ov = overrides_for(best)
    if args.apply:
        with open(OUT, "w") as f:
            json.dump(ov, f, indent=1)
    print(json.dumps({"decision": "flip", "to": best["variant"],
                      "gain": round(gain, 4), "applied": args.apply,
                      "overrides": ov}))


if __name__ == "__main__":
    main()
