"""Perf sweep for the single-chip GPT training step.

Times the engine's fused train step over a grid of (batch, flash blocks,
remat policy) on the local chip and prints one JSON line per config —
the tuning harness behind bench.py's headline number (analog of the
reference's perf sweep scripts, ref: tests/model/Megatron_GPT2/run_perf*).

Usage: python tools/perf_sweep.py [preset] [steps]
"""

import json
import sys

import jax

sys.path.insert(0, ".")

from bench import run_config  # noqa: E402


def main():
    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2-medium"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    seq = 1024
    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    grid = [
        # (batch, flash_block, extra ds-config)
        (8, 512, {}),
        (16, 512, {}),
        (32, 512, {}),
        (16, 256, {}),
        (16, 1024, {}),
        (16, 512, {"bf16": {"enabled": True, "memory_efficient": True}}),
    ]
    for batch, fb, extra in grid:
        overrides = {"zero_optimization": {"stage": 1}}
        overrides.update(extra)
        try:
            dt, tps, mfu = run_config(preset, batch, seq, steps,
                                      overrides, on_tpu, flash_block=fb)
            print(json.dumps({
                "preset": preset, "batch": batch, "flash_block": fb,
                "extra": extra,
                "step_ms": round(dt * 1e3, 2),
                "tokens_per_s": round(tps, 1), "mfu": round(mfu, 4)}),
                flush=True)
        except Exception as e:  # OOM etc — report and continue
            print(json.dumps({
                "preset": preset, "batch": batch, "flash_block": fb,
                "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
