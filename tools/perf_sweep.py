"""Perf sweep for the single-chip GPT training step.

Times the engine's fused train step over a grid of (batch, flash blocks,
remat policy) on the local chip and prints one JSON line per config —
the tuning harness behind bench.py's headline number (analog of the
reference's perf sweep scripts, ref: tests/model/Megatron_GPT2/run_perf*).

Usage: python tools/perf_sweep.py [preset] [steps]
"""

import itertools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")


def time_config(preset_name, batch, seq, bq, bkv, remat_policy, steps=10,
                remat=True, zero_stage=1):
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    cfg = gpt.preset(preset_name, max_seq_len=seq, dtype=jnp.bfloat16,
                     remat=remat, remat_policy=remat_policy,
                     use_flash_attention=on_tpu,
                     flash_block_q=bq, flash_block_kv=bkv)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_config = {
        "train_batch_size": batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": zero_stage},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.1}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    data = {"tokens": tokens}
    jax.block_until_ready(engine.train_batch(data))
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(data)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    tps = batch * seq / dt
    mfu = tps * gpt.train_flops_per_token(cfg, seq) / 197e12
    del engine, params
    return dt, tps, mfu


def main():
    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2-medium"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    seq = 1024
    grid = [
        # (batch, bq, bkv, remat, policy)
        (8, 512, 512, True, "selective"),    # round-1 bench config
        (16, 512, 512, True, "selective"),
        (32, 512, 512, True, "selective"),
        (16, 256, 512, True, "selective"),
        (16, 512, 1024, True, "selective"),
        (16, 1024, 512, True, "selective"),
        (16, 256, 256, True, "selective"),
        (16, 512, 512, True, "full"),
        (16, 512, 512, False, "selective"),
    ]
    for batch, bq, bkv, remat, pol in grid:
        try:
            dt, tps, mfu = time_config(preset, batch, seq, bq, bkv, pol,
                                       steps=steps, remat=remat)
            print(json.dumps({
                "preset": preset, "batch": batch, "bq": bq, "bkv": bkv,
                "remat": remat, "policy": pol,
                "step_ms": round(dt * 1e3, 2),
                "tokens_per_s": round(tps, 1), "mfu": round(mfu, 4)}),
                flush=True)
        except Exception as e:  # OOM etc — report and continue
            print(json.dumps({
                "preset": preset, "batch": batch, "bq": bq, "bkv": bkv,
                "remat": remat, "policy": pol,
                "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
