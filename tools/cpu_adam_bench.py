"""CPU Adam/Adagrad throughput micro-benchmark.

Analog of the reference's `tests/perf/adam_test.py` (CPU Adam throughput
over a synthetic parameter) for the AVX C++ step in
`csrc/adam/cpu_adam.cpp`: elements/second of the fused
momentum+variance+update loop vs a vectorized numpy reference — the
number that bounds the host half of the ZeRO-Offload 3-stage pipeline
(`runtime/zero/offload.py`; the loopback tool consumes exactly these
per-shard Adam durations).

Usage: python tools/cpu_adam_bench.py [elems ...]   (default 1M 8M 64M)
Prints one JSON line per size.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam


def numpy_adam_step(p, g, m, v, step, lr=1e-3, b1=0.9, b2=0.999,
                    eps=1e-8, wd=0.0):
    m *= b1
    m += (1 - b1) * g
    v *= b2
    v += (1 - b2) * g * g
    bc1 = 1 - b1 ** step
    bc2 = 1 - b2 ** step
    p -= lr * (m / bc1) / (np.sqrt(v / bc2) + eps)
    return p


def bench(elems: int, iters: int = 10):
    r = np.random.default_rng(0)
    params = r.standard_normal(elems).astype(np.float32)
    grads = r.standard_normal(elems).astype(np.float32)

    opt = DeepSpeedCPUAdam(lr=1e-3)
    opt.step("w", params.copy(), grads)          # state init + warmup
    p_c = params.copy()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        opt.step("w", p_c, grads)
        ts.append(time.perf_counter() - t0)
    dt_c = sorted(ts)[len(ts) // 2]              # median: GC/scheduler-robust

    m = np.zeros(elems, np.float32)
    v = np.zeros(elems, np.float32)
    p_n = params.copy()
    numpy_adam_step(p_n, grads, m, v, 1)          # warmup allocs
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        numpy_adam_step(p_n, grads, m, v, i + 2)
        ts.append(time.perf_counter() - t0)
    dt_n = sorted(ts)[len(ts) // 2]

    print(json.dumps({
        "metric": "cpu_adam_throughput",
        "elems": elems,
        "cxx_ms": round(dt_c * 1e3, 2),
        "cxx_gelems_per_s": round(elems / dt_c / 1e9, 3),
        "numpy_ms": round(dt_n * 1e3, 2),
        "speedup_vs_numpy": round(dt_n / dt_c, 2),
    }), flush=True)


def main():
    sizes = [int(a) for a in sys.argv[1:]] or [1_000_000, 8_000_000,
                                               64_000_000]
    for n in sizes:
        bench(n)


if __name__ == "__main__":
    main()
