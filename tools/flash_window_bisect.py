"""Bisect the sliding-window Mosaic compile hang (VERDICT r4 #2).

The round-4 on-chip smoke hung the remote Mosaic compile helper for
~20min on the `window` case and re-wedged the rig (STATUS.md). The
window path differs from the proven `plain` causal case by exactly
three static constructs:

  A. the index-map lo-clamp  (_causal_kv_index_map's jnp.maximum(ki, lo)
     with a negative-dividend floordiv)            -> case "clamp"
  B. the band-aware grid skip (_band_run's window term) -> case "bandrun"
  C. the in-body window mask (_window_mask)        -> case "maskonly"

Each case compiles ONE minimized forward kernel with only that
construct enabled, in its OWN subprocess with a timeout — a hang
classifies the construct instead of wedging the queue. "control"
(plain causal) and "full"/"masked" (the two shipping window impls,
parity-checked vs the jnp reference) bracket the bisection;
"bwd-full" compiles the backward pair. chip_queue runs this dead-last
in the quarantined window item.

Usage: python tools/flash_window_bisect.py [case ...]
"""

import json
import sys

sys.path.insert(0, ".")

from tools._subproc import run_json  # noqa: E402

CASES = ("control", "maskonly", "clamp", "bandrun", "masked", "full",
         "bwd-masked", "bwd-full")

CODE = """
import json, sys
sys.path.insert(0, '.')
import jax, jax.numpy as jnp, numpy as np
from deepspeed_tpu.ops.attention import flash as F

case = {case!r}
W = 256
B, H, S, D = 1, 4, 1024, 64
ks = [jax.random.PRNGKey(i) for i in range(3)]
q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)

# per-case construct isolation (single-purpose subprocess: patching the
# module is the cheapest way to switch one static construct at a time)
window = W
parity = True
if case == "control":
    window = None
elif case == "maskonly":          # C only (== the "masked" impl)
    window = ("masked", W)
elif case == "clamp":             # A only: clamp active, mask+skip off
    _orig_map = F._causal_kv_index_map
    F._band_run = lambda qi, ki, bq, bkv, causal, w, q_off=0: \\
        (qi * bq + bq - 1 + q_off >= ki * bkv) if causal else True
    F._window_mask = lambda s, rows, cols, w: s
    parity = False                # not a correct config; compile-only
elif case == "bandrun":           # B only: skip active, clamp+mask off
    _orig = F._causal_kv_index_map
    F._causal_kv_index_map = \\
        lambda bq, bkv, nkv, w=None, q_off=0: _orig(bq, bkv, nkv, None,
                                                    q_off)
    F._window_mask = lambda s, rows, cols, w: s
    parity = False
elif case in ("full", "bwd-full"):
    window = W
elif case == "bwd-masked":
    window = ("masked", W)

grad = case.startswith("bwd-")
if grad:
    def f(q, k, v):
        o = F._flash(q, k, v, None, None, None, True, 0.125, 256, 256,
                     window, None, None)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    fn = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
else:
    fn = jax.jit(lambda q, k, v: F._flash_fwd(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), None, None, None, True, 0.125,
        256, 256, window)[0])

fn.lower(q, k, v).compile()
out = {{"case": case, "compiled": True}}
if parity and not grad:
    o = fn(q, k, v).transpose(0, 2, 1, 3)
    ref = F.mha_reference(q, k, v, causal=True, scale=0.125, window=W)
    err = float(jnp.max(jnp.abs(o.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    out["max_abs_err"] = round(err, 5)
    out["parity"] = err < 0.06
elif parity and grad:
    g = fn(q, k, v)
    out["grads_finite"] = all(bool(jnp.all(jnp.isfinite(
        x.astype(jnp.float32)))) for x in g)
print(json.dumps(out))
"""


def main():
    names = sys.argv[1:] or list(CASES)
    for case in names:
        if case not in CASES:
            print(json.dumps({"case": case, "error": "unknown"}),
                  flush=True)
            continue
        # 900s: far above any sane compile, far below the observed
        # ~20min helper wedge — a hang classifies the construct
        run_json([sys.executable, "-c", CODE.format(case=case)], 900,
                 {"case": case, "verdict": "COMPILE HUNG (classified)"})


if __name__ == "__main__":
    main()
