"""Capture + analyze a device trace of the training step.

Runs N traced train steps (any bench config) and aggregates the XPlane
Chrome-trace events into a per-op-category time breakdown — the tool that
turns "MFU is X%" into "Y ms goes to fusions / dots / the flash custom
call / copies". TPU analog of reading an nsys timeline of the reference's
NVTX ranges (ref: deepspeed/utils/nvtx.py + docs/_tutorials/pytorch-profiler.md).

Usage:
  python tools/trace_analyze.py run [preset] [batch] [remat] [loss_chunk]
      — trains 2 traced steps on the local chip, writes /tmp/dstrace,
        then analyzes it.
  python tools/trace_analyze.py read /tmp/dstrace
      — re-analyze an existing capture.
  python tools/trace_analyze.py serve /tmp/serve_trace.json
      — analyze a serving-telemetry Perfetto export
        (deepspeed_tpu/telemetry, docs/OBSERVABILITY.md): per-request
        lifecycle spans, step-phase breakdown, injected-fault timeline.
  python tools/trace_analyze.py fleet /tmp/router_trace.json
      — analyze a ROUTER-level export: per-replica dispatch counts,
        breaker/health timeline, drains/restarts/fleet-shape changes
        and the autoscale decision timeline with each decision's
        triggering window metrics.
  python tools/trace_analyze.py cost <artifact-or-snapshot.json>
      — per-phase / per-tenant cost summary (FLOPs, HBM bytes, KV
        block-seconds) from either a flight-recorder postmortem
        artifact (CRC-verified) or a live ``CostAccountant.snapshot()``
        JSON dump.

``serve``/``fleet``/``cost`` accept ``--json``: print the full summary
dict as one JSON document (stable schema — the same dict the tests
assert on) instead of the human report.
"""

import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, ".")


def categorize(name: str) -> str:
    n = name.lower()
    if "custom-call" in n or "tpu_custom_call" in n or "pallas" in n:
        return "pallas kernels (flash etc.)"
    if n.startswith("fusion") or ".fusion" in n:
        return "XLA fusions (elementwise/LN/softmax)"
    if "convolution" in n or n.startswith("dot") or "einsum" in n or \
            "matmul" in n or ".dot" in n:
        return "matmuls (MXU)"
    if "all-reduce" in n or "all-gather" in n or "reduce-scatter" in n or \
            "all-to-all" in n or "collective" in n or "permute" in n:
        return "collectives"
    if "copy" in n or "transpose" in n or "reshape" in n or "bitcast" in n:
        return "copies/transposes"
    if "dynamic-update-slice" in n or "dynamic-slice" in n or "slice" in n \
            or "scatter" in n or "gather" in n or "pad" in n or "concat" in n:
        return "slice/gather/pad"
    if "infeed" in n or "outfeed" in n or "host" in n or "transfer" in n:
        return "host transfer"
    return "other"


def analyze(log_dir: str, top: int = 25):
    files = glob.glob(os.path.join(
        log_dir, "**", "*.trace.json.gz"), recursive=True)
    assert files, f"no trace.json.gz under {log_dir}"
    path = max(files, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])

    # device-lane complete events only (TensorCore ops have 'dur')
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name" and "args" in e}
    dev_pids = {p for p, n in pid_names.items()
                if "/device:TPU" in n or "TPU Core" in n or "TensorCore" in n}

    if not dev_pids:
        print("WARNING: no TPU device lane matched — totals below include "
              "HOST lanes and are not a device-time breakdown",
              file=sys.stderr)

    by_op = collections.Counter()
    by_cat = collections.Counter()
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if dev_pids and e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        dur = e["dur"]  # microseconds
        by_op[name] += dur
        by_cat[categorize(name)] += dur
        total += dur

    print(json.dumps({"trace": os.path.relpath(path, log_dir),
                      "total_device_us": round(total, 1)}))
    print("\n-- by category --")
    for cat, us in by_cat.most_common():
        print(f"{us/1e3:10.2f} ms  {100*us/max(total,1e-9):5.1f}%  {cat}")
    print(f"\n-- top {top} ops --")
    for name, us in by_op.most_common(top):
        print(f"{us/1e3:10.2f} ms  {100*us/max(total,1e-9):5.1f}%  {name[:110]}")


def _load_trace(path: str) -> dict:
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def analyze_serving_trace(path: str, quiet: bool = False) -> dict:
    """Summarize a serving-telemetry Chrome-trace export
    (``RequestTracer.to_chrome_trace``): per-request lifecycle span
    sequences (queued/prefill/decode, terminal state), scheduler
    step-phase totals, and the injected-fault timeline. Returns the
    summary dict (tests assert on it); prints it unless ``quiet``."""
    trace = _load_trace(path)
    events = trace.get("traceEvents", [])
    requests, phase_us, faults = {}, collections.Counter(), []
    for e in events:
        ph, cat = e.get("ph"), e.get("cat")
        if ph == "X" and cat == "request":
            rid = e.get("args", {}).get("rid")
            requests.setdefault(rid, {"spans": [], "state": None,
                                      "span_us": 0.0})
            requests[rid]["spans"].append((e["ts"], e["name"]))
            requests[rid]["span_us"] += e.get("dur", 0.0)
            state = e.get("args", {}).get("state")
            if state:
                requests[rid]["state"] = state
        elif ph == "X" and cat == "step":
            phase_us[e["name"]] += e.get("dur", 0.0)
        elif ph == "i" and cat == "fault":
            faults.append(dict(e.get("args", {}), ts=e.get("ts")))
    for r in requests.values():
        r["spans"] = [name for _, name in sorted(r["spans"],
                                                 key=lambda s: s[0])]
    summary = {
        "n_events": len(events),
        "dropped_events": trace.get("dropped_events", 0),
        "requests": requests,
        "phase_us": {k: round(v, 1) for k, v in phase_us.items()},
        "faults": faults,
    }
    if not quiet:
        print(json.dumps({"trace": path, "n_events": len(events),
                          "requests": len(requests),
                          "faults": len(faults)}))
        print("\n-- step phases (sampled) --")
        total = sum(phase_us.values())
        for name, us in phase_us.most_common():
            print(f"{us/1e3:10.2f} ms  {100*us/max(total,1e-9):5.1f}%  {name}")
        print("\n-- requests --")
        for rid, r in requests.items():
            print(f"  {rid}: {' > '.join(r['spans'])}"
                  f"  [{r['state'] or 'in flight'}]"
                  f"  {r['span_us']/1e3:.2f} ms")
        if faults:
            print("\n-- injected faults --")
            for f in faults:
                print(f"  step {f.get('step')}: {f.get('site')}"
                      f":{f.get('kind')} (visit {f.get('visit')})")
    return summary


def analyze_fleet_trace(path: str, quiet: bool = False) -> dict:
    """Summarize a ROUTER-level Perfetto export: per-replica dispatch
    occupancy, the breaker/health timeline, drains, warm restarts,
    fleet-shape (``scale``) changes, router-side sheds and the full
    autoscale decision timeline (each decision instant carries the
    windowed metrics that triggered it — the reconstructability
    contract of docs/OBSERVABILITY.md). Returns the summary dict
    (tests assert on it); prints it unless ``quiet``."""
    trace = _load_trace(path)
    events = trace.get("traceEvents", [])
    dispatch_per_replica = collections.Counter()
    resumed = 0
    breaker, drains, restarts, scale, decisions, degraded = \
        [], [], [], [], [], []
    sheds = 0
    for e in events:
        if e.get("ph") != "i" or e.get("cat") != "scheduler":
            continue
        name, a = e.get("name"), dict(e.get("args", {}))
        a["ts"] = e.get("ts")
        if name == "dispatch":
            dispatch_per_replica[a.get("replica")] += 1
            resumed += bool(a.get("resumed"))
        elif name == "breaker":
            breaker.append(a)
        elif name == "drain":
            drains.append(a)
        elif name == "restart":
            restarts.append(a)
        elif name == "scale":
            scale.append(a)
        elif name == "autoscale":
            decisions.append(a)
        elif name == "shed":
            sheds += 1
        elif name == "degraded":
            degraded.append(a)
    by_action = collections.Counter(d.get("action") for d in decisions)
    summary = {
        "n_events": len(events),
        "dispatch": {
            "total": sum(dispatch_per_replica.values()),
            "per_replica": {str(k): v for k, v
                            in sorted(dispatch_per_replica.items())},
            "resumed": resumed,
        },
        "breaker": breaker,
        "drains": drains,
        "restarts": restarts,
        "scale": scale,
        "autoscale": {"decisions": decisions,
                      "by_action": dict(by_action)},
        "sheds": sheds,
        "degraded": degraded,
    }
    if not quiet:
        print(json.dumps({
            "trace": path, "n_events": len(events),
            "dispatched": summary["dispatch"]["total"],
            "breaker_transitions": len(breaker), "drains": len(drains),
            "restarts": len(restarts), "scale_changes": len(scale),
            "autoscale_decisions": len(decisions), "sheds": sheds}))
        if dispatch_per_replica:
            print("\n-- dispatches by replica --")
            for idx, n in sorted(dispatch_per_replica.items()):
                print(f"  replica {idx}: {n}"
                      + (f"  ({resumed} resumed fleet-wide)"
                         if idx == min(dispatch_per_replica) and resumed
                         else ""))
        if breaker:
            print("\n-- health timeline --")
            for b in breaker:
                print(f"  step {b.get('step')}: replica {b.get('replica')}"
                      f" {b.get('prev')} -> {b.get('state')}"
                      f" ({b.get('reason', '')})")
        if scale:
            print("\n-- fleet shape --")
            for s in scale:
                print(f"  step {s.get('step')}: {s.get('action')}"
                      f" replica {s.get('replica')}"
                      f" ({s.get('reason', '')})")
        acted = [d for d in decisions if d.get("action") != "noop"]
        if decisions:
            print(f"\n-- autoscale decisions "
                  f"({len(decisions)} evals, {len(acted)} actions) --")
            for d in acted:
                print(f"  step {d.get('step')}: {d.get('action')}"
                      f"  p99_ttft={d.get('p99_ttft'):.4g}"
                      f" (slo {d.get('ttft_slo')},"
                      f" {int(d.get('window_count', 0))} obs)"
                      f" load={d.get('load')}"
                      f" active={d.get('active_replicas')}")
    return summary


def analyze_cost(path: str, quiet: bool = False) -> dict:
    """Per-phase / per-tenant cost summary from a cost-accounting
    snapshot. Accepts either a flight-recorder postmortem artifact
    (``{"version", "crc32", "body"}`` — CRC-verified via
    ``tools/postmortem.py``'s stdlib reader) or a raw
    ``CostAccountant.snapshot()`` JSON file. Returns the summary dict
    (tests assert on it); prints it unless ``quiet``."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "body" in doc and "crc32" in doc:
        from tools.postmortem import verify_artifact
        verify_artifact(doc)
        costs = doc["body"].get("costs") or {}
        source = "postmortem"
    else:
        costs = doc
        source = "snapshot"
    per_class = costs.get("totals") or {}
    tenants = costs.get("tenants") or {}

    def _fold(fp):
        out = {"flops": 0, "hbm_bytes": 0, "dispatches": 0,
               "block_seconds": int(fp.get("block_seconds", 0))}
        for cls, c in fp.items():
            if isinstance(c, dict):
                for k in ("flops", "hbm_bytes", "dispatches"):
                    out[k] += int(c.get(k, 0))
        return out

    summary = {
        "source": source,
        "flops_total": int(costs.get("flops_total") or 0),
        "hbm_bytes_total": int(costs.get("hbm_bytes_total") or 0),
        "block_seconds_total": int(costs.get("block_seconds_total") or 0),
        "per_class": per_class,
        "per_tenant": {tid: _fold(fp) for tid, fp
                       in sorted(tenants.items())},
    }
    if not quiet:
        print(json.dumps({"file": path, "source": source,
                          "flops_total": summary["flops_total"],
                          "hbm_bytes_total": summary["hbm_bytes_total"],
                          "kv_block_seconds":
                          summary["block_seconds_total"]}))
        if per_class:
            print("\n-- by dispatch class --")
            for cls, c in sorted(per_class.items()):
                print(f"  {cls:<8} {c.get('dispatches', 0):>8} dispatches"
                      f" {c.get('flops', 0):>16} flops"
                      f" {c.get('hbm_bytes', 0):>16} bytes")
        if summary["per_tenant"]:
            print("\n-- by tenant --")
            for tid, t in summary["per_tenant"].items():
                print(f"  {tid:<14} {t['flops']:>16} flops"
                      f" {t['hbm_bytes']:>16} bytes"
                      f" {t['block_seconds']:>8} block-s")
    return summary


def run():
    import jax
    import numpy as np

    preset = sys.argv[2] if len(sys.argv) > 2 else "gpt2-1.5b"
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 16
    remat = sys.argv[4] if len(sys.argv) > 4 else "full"
    loss_chunk = int(sys.argv[5]) if len(sys.argv) > 5 else 2048

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    import jax.numpy as jnp

    cfg = gpt.preset(preset, max_seq_len=1024, dtype=jnp.bfloat16,
                     remat=True, remat_policy=remat,
                     use_flash_attention=True, flash_block_q=1024,
                     flash_block_kv=1024, loss_chunk=loss_chunk)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": batch,
                "bf16": {"enabled": True, "memory_efficient": True},
                "zero_optimization": {"stage": 3},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "steps_per_print": 10_000})
    del params
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, 1025)).astype(np.int32)
    data = {"tokens": tokens}
    jax.block_until_ready(engine.train_batch(data)["loss"])  # compile

    log_dir = "/tmp/dstrace"
    engine.start_trace(log_dir, steps=2)
    for _ in range(2):
        float(engine.train_batch(data)["loss"])
    analyze(log_dir)


if __name__ == "__main__":
    _as_json = "--json" in sys.argv[2:]
    if sys.argv[1:] and sys.argv[1] == "read":
        analyze(sys.argv[2])
    elif sys.argv[1:] and sys.argv[1] == "serve":
        s = analyze_serving_trace(sys.argv[2], quiet=_as_json)
        if _as_json:
            print(json.dumps(s, sort_keys=True))
    elif sys.argv[1:] and sys.argv[1] == "fleet":
        s = analyze_fleet_trace(sys.argv[2], quiet=_as_json)
        if _as_json:
            print(json.dumps(s, sort_keys=True))
    elif sys.argv[1:] and sys.argv[1] == "cost":
        s = analyze_cost(sys.argv[2], quiet=_as_json)
        if _as_json:
            print(json.dumps(s, sort_keys=True))
    else:
        run()
