"""A/B probe for the 1.5B single-chip headline config.

Each variant runs in a fresh subprocess (the rig's remote compile helper
can 500 on repeat compiles in one process). Prints one JSON line per
variant. Usage: python tools/headline_probe.py [variant ...]
"""

import json
import subprocess
import sys

sys.path.insert(0, ".")

VARIANTS = {
    # name: (batch, remat_policy, loss_chunk)
    "b16-full": (16, "full", 0),
    "b16-full-ce": (16, "full", 2048),
    "b16-flashonly-ce": (16, "flash_only", 2048),
    "b24-full-ce": (24, "full", 2048),
    "b24-flashonly-ce": (24, "flash_only", 2048),
    "b32-full-ce": (32, "full", 2048),
    "b16-sel-ce": (16, "selective", 2048),
}


def run_one(name):
    batch, pol, lc = VARIANTS[name]
    code = (
        "import sys, json; sys.path.insert(0, '.')\n"
        "from bench import run_config, MFU_BAR\n"
        f"dt, tps, mfu = run_config('gpt2-1.5b', {batch}, 1024, 8,\n"
        "    {'bf16': {'enabled': True, 'memory_efficient': True},\n"
        "     'zero_optimization': {'stage': 3}},\n"
        f"    True, flash_block=1024, remat_pol='{pol}', loss_chunk={lc})\n"
        f"print(json.dumps({{'variant': '{name}', 'batch': {batch},\n"
        f"    'remat': '{pol}', 'loss_chunk': {lc},\n"
        "    'step_ms': round(dt*1e3, 1), 'tokens_per_s': round(tps, 1),\n"
        "    'mfu': round(mfu, 4), 'vs_bar': round(mfu/MFU_BAR, 3)}))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=2400)
    out = None
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("{"):
            out = line
            break
    if out:
        print(out, flush=True)
    else:
        print(json.dumps({"variant": name, "rc": r.returncode,
                          "err": r.stderr[-400:]}), flush=True)


def main():
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        run_one(n)


if __name__ == "__main__":
    main()
