"""A/B probe for single-chip bench configs.

A thin wrapper over ``bench.run_config`` (same engine path, warmup,
per-step-synced median timing and MFU accounting as the driver bench)
run once per variant in a fresh subprocess (the rig's remote compile
helper can 500 on repeat compiles in one process). Prints one JSON line
per variant.

Every variant passes through the analytic compile-memory guard
(deepspeed_tpu/utils/hbm.py) BEFORE any backend contact: borderline-HBM
compiles wedge this rig's remote compile service (PERF.md incident log),
so unsafe variants are skipped with an explanatory JSON line instead of
being attempted. Reference analog: the autotuner prunes configs by
memory model before running them (ref: autotuning/autotuner.py:396).

Usage: python tools/headline_probe.py [variant ...]
"""

import json
import os
import sys

sys.path.insert(0, ".")

from tools._subproc import run_json  # noqa: E402

_D = dict(preset="gpt2-1.5b", batch=16, remat=True, pol="full",
          lc=2048, stage=3, me=True, fb=1024, fbkv=None,
          bwdq=None, bwdkv=None, seq=1024, steps=8)


def _v(**kw):
    d = dict(_D)
    d.update(kw)
    return d


VARIANTS = {
    # --- 1.5B headline family ---------------------------------------
    "b16-full": _v(lc=0),
    "b16-full-ce": _v(),
    "b16-flashonly-ce": _v(pol="flash_only"),   # guard: refused (grind)
    # flash_only FITS at b12 (guard: 14.26GiB) — skips the flash-fwd
    # recompute the b16 variant died trying to buy
    "b12-flashonly-ce": _v(batch=12, pol="flash_only"),
    # offload_flash: flash residuals stream to pinned host — full-remat
    # HBM footprint WITH the recompute skip, at full batch 16
    "b16-offloadflash-ce": _v(pol="offload_flash"),
    "b20-full-ce": _v(batch=20),
    "b22-full-ce": _v(batch=22),
    "b24-full-ce": _v(batch=24),                # guard: refused
    "b32-full-ce": _v(batch=32),                # guard: refused
    "b16-sel-ce": _v(pol="selective"),          # guard: refused
    # backward-tile tuning at the headline config (fwd stays 1024)
    "b16-bwd512": _v(bwdq=512, bwdkv=512),
    "b16-bwdq512": _v(bwdq=512),
    "b16-bwdkv512": _v(bwdkv=512),
    "b16-bwd256": _v(bwdq=256, bwdkv=256),
    # fwd-tile asymmetry
    "b16-fbq512": _v(fb=512, fbkv=1024),
    "b16-fbkv512": _v(fb=1024, fbkv=512),
    # combined levers: offload_flash (skip the flash-fwd recompute) x
    # bwd tiles / batch growth — if the individual levers pay, their
    # combination is the plausible headline winner; all guard-checked
    # like everything else before any backend contact
    "b16-offloadflash-bwd512": _v(pol="offload_flash", bwdq=512,
                                  bwdkv=512),
    "b18-offloadflash-ce": _v(batch=18, pol="offload_flash"),
    "b20-offloadflash-ce": _v(batch=20, pol="offload_flash"),
    "b12-flashonly-bwd512": _v(batch=12, pol="flash_only", bwdq=512,
                               bwdkv=512),
    # --- medium secondary family ------------------------------------
    "med-b8": _v(preset="gpt2-medium", batch=8, pol="selective", lc=0,
                 stage=1, me=False),
    "med-b8-noremat": _v(preset="gpt2-medium", batch=8, remat=False,
                         pol="selective", stage=1, me=False),
    "med-b16-noremat": _v(preset="gpt2-medium", batch=16, remat=False,
                          pol="selective", stage=1, me=False),  # refused
    "med-b16-ce": _v(preset="gpt2-medium", batch=16, pol="selective",
                     stage=1, me=False),
}

CODE = """
import sys, json
sys.path.insert(0, '.')
from bench import run_config, MFU_BAR

s = {spec!r}
overrides = {{"zero_optimization": {{"stage": s["stage"]}}}}
if s["me"]:
    overrides["bf16"] = {{"enabled": True, "memory_efficient": True}}
on_tpu = s.get("on_tpu", True)
dt, tps, mfu = run_config(s["preset"], s["batch"], s["seq"], s["steps"],
                          overrides, on_tpu,
                          flash_block=s["fb"], flash_block_kv=s["fbkv"],
                          remat_pol=s["pol"], loss_chunk=s["lc"],
                          remat=s["remat"], bwd_block_q=s["bwdq"],
                          bwd_block_kv=s["bwdkv"])
print(json.dumps({{"variant": {name!r}, "preset": s["preset"],
    "batch": s["batch"], "remat": (s["pol"] if s["remat"] else "none"),
    "loss_chunk": s["lc"], "bwd_blocks": [s["bwdq"], s["bwdkv"]],
    "fwd_blocks": [s["fb"], s["fbkv"] or s["fb"]],
    "step_ms": round(dt*1e3, 1), "tokens_per_s": round(tps, 1),
    "mfu": round(mfu, 4), "vs_bar": round(mfu/MFU_BAR, 3)}}))
"""


def guard_variant(name, s, hbm_gib=None):
    """Analytic safety decision — NO backend contact (a wedged tunnel
    hangs jax.devices(); default capacity comes from DS_TPU_HBM_GIB or
    falls back to the 16GiB v5e so the decision stays consistent with
    utils/hbm.py's device table without requiring a live backend)."""
    if hbm_gib is None:
        hbm_gib = float(os.environ.get("DS_TPU_HBM_GIB", 16))
    import jax.numpy as jnp
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.utils import hbm
    seq = s.get("seq", 1024)
    cfg = gpt.preset(s["preset"], max_seq_len=seq, dtype=jnp.bfloat16,
                     remat=s["remat"], remat_policy=s["pol"],
                     loss_chunk=s["lc"])
    est = hbm.estimate_gpt_train_bytes(
        cfg, s["batch"], seq, memory_efficient=s["me"],
        precision="bf16")
    return hbm.check_compile_safe(est, hbm_gib * hbm.GiB)


def main():
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        spec = VARIANTS[n]
        ok, msg = guard_variant(n, spec)
        if not ok:
            print(json.dumps({"variant": n, "skipped": "memory guard",
                              "why": msg}), flush=True)
            continue
        run_json([sys.executable, "-c", CODE.format(spec=spec, name=n)],
                 2400, {"variant": n})


if __name__ == "__main__":
    main()
