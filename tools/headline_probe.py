"""A/B probe for single-chip bench configs.

A thin wrapper over ``bench.run_config`` (same engine path, warmup,
per-step-synced median timing and MFU accounting as the driver bench)
run once per variant in a fresh subprocess (the rig's remote compile
helper can 500 on repeat compiles in one process). Prints one JSON line
per variant. Usage: python tools/headline_probe.py [variant ...]
"""

import sys

sys.path.insert(0, ".")

from tools._subproc import run_json  # noqa: E402

# name: (preset, batch, remat(True/False), remat_policy, loss_chunk, stage,
#        memory_efficient)
VARIANTS = {
    "b16-full": ("gpt2-1.5b", 16, True, "full", 0, 3, True),
    "b16-full-ce": ("gpt2-1.5b", 16, True, "full", 2048, 3, True),
    "b16-flashonly-ce": ("gpt2-1.5b", 16, True, "flash_only", 2048, 3, True),
    "b24-full-ce": ("gpt2-1.5b", 24, True, "full", 2048, 3, True),
    "b32-full-ce": ("gpt2-1.5b", 32, True, "full", 2048, 3, True),
    "b16-sel-ce": ("gpt2-1.5b", 16, True, "selective", 2048, 3, True),
    "med-b8": ("gpt2-medium", 8, True, "selective", 0, 1, False),
    "med-b8-noremat": ("gpt2-medium", 8, False, "selective", 2048, 1, False),
    "med-b16-noremat": ("gpt2-medium", 16, False, "selective", 2048, 1, False),
    "med-b16-ce": ("gpt2-medium", 16, True, "selective", 2048, 1, False),
}

CODE = """
import sys, json
sys.path.insert(0, '.')
from bench import run_config, MFU_BAR

preset, batch, remat, pol, lc, stage, me = {spec!r}
overrides = {{"zero_optimization": {{"stage": stage}}}}
if me:
    overrides["bf16"] = {{"enabled": True, "memory_efficient": True}}
dt, tps, mfu = run_config(preset, batch, 1024, 8, overrides, True,
                          flash_block=1024, remat_pol=pol, loss_chunk=lc,
                          remat=remat)
print(json.dumps({{"variant": {name!r}, "preset": preset, "batch": batch,
    "remat": (pol if remat else "none"), "loss_chunk": lc,
    "step_ms": round(dt*1e3, 1), "tokens_per_s": round(tps, 1),
    "mfu": round(mfu, 4), "vs_bar": round(mfu/MFU_BAR, 3)}}))
"""


def main():
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        run_json([sys.executable, "-c",
                  CODE.format(spec=VARIANTS[n], name=n)],
                 2400, {"variant": n})


if __name__ == "__main__":
    main()
