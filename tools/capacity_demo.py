"""Capacity demo: train a GPT larger than device HBM via the ZeRO-Infinity
parameter tier (runtime/zero/param_offload.py).

Proof analog of the reference's "13B params on one 32GB V100"
(ref docs/_pages/features.md:116). Prints one JSON line per step and a
final summary with peak params/chip.

Usage: python tools/capacity_demo.py [preset] [steps] [micro_batch] [seq]
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    preset = sys.argv[1] if len(sys.argv) > 1 else "gpt2-4b"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else 1024

    on_tpu = "tpu" in (jax.devices()[0].platform +
                       jax.devices()[0].device_kind).lower()
    cfg = gpt.preset(preset, max_seq_len=seq, dtype=jnp.bfloat16,
                     remat=True, use_flash_attention=on_tpu,
                     flash_block_q=512, flash_block_kv=512)
    fac = gpt.host_param_factory(0, cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=fac,
        config={
            "train_batch_size": batch,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3,
                                  "offload_param": {"device": "cpu"}},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        })
    r = np.random.default_rng(0)
    data = {"tokens": r.integers(0, cfg.vocab_size,
                                 (batch, seq + 1)).astype(np.int32)}
    for i in range(steps):
        t0 = time.perf_counter()
        m = eng.train_batch(data)
        print(json.dumps({
            "step": i, "loss": round(m["loss"], 4),
            "grad_norm": round(m["grad_norm"], 3),
            "step_s": round(time.perf_counter() - t0, 1)}), flush=True)
    print(json.dumps({
        "metric": "peak_params_per_chip_with_offload",
        "value": eng.n_params,
        "model": preset,
        "n_layers": cfg.n_layers, "d_model": cfg.d_model,
        "device": jax.devices()[0].device_kind,
        "device_working_set_gb": round(
            eng.device_memory_bytes() / 1e9, 2),
        "groups": eng.n_groups, "group_size": eng.group_size,
    }))


if __name__ == "__main__":
    main()
