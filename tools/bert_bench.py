"""BERT-large pretraining throughput — the reference's headline benchmark
(ref: docs/_tutorials/bert-pretraining.md:388 — 64 TFLOPS / 272
samples/s/GPU at seq128, 53 TFLOPS / 52 samples/s at seq512 on one V100).

Prints one JSON line per (seq, batch) config with samples/s and achieved
model TFLOPS on this chip (per-step-synced median timing, see PERF.md).

Usage: python tools/bert_bench.py [steps]
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")


def mlm_batch(rng, vocab, batch, seq, mask_frac=0.15):
    tokens = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    labels = np.where(rng.random((batch, seq)) < mask_frac, tokens, -1)
    return {"tokens": tokens, "mlm_labels": labels.astype(np.int32)}


def flops_per_sample(cfg, seq):
    """Megatron-style fwd+bwd matmul flops for one MLM sample."""
    d, L, ff, V = cfg.d_model, cfg.n_layers, 4 * cfg.d_model, cfg.vocab_size
    per_layer = 4 * d * d + 2 * d * ff          # qkv+proj + mlp
    attn = 2 * L * d * seq                      # scores + weighted sum
    head = d * V + d * d                        # mlm decoder + transform
    return 6.0 * seq * (L * per_layer + head) + 6.0 * seq * attn


def run(seq, batch, steps):
    import deepspeed_tpu
    from deepspeed_tpu.models import bert

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = bert.preset("bert-large", max_seq_len=max(seq, 128),
                      dropout=0.0, dtype=jnp.bfloat16,
                      remat=True, remat_policy="full",
                      loss_chunk=2048 if on_tpu else 0)
    if on_tpu:
        # refuse borderline-HBM compiles before any backend contact —
        # one unguarded compile can wedge the rig (utils/hbm.py, PERF.md)
        from deepspeed_tpu.utils import hbm
        hbm.guard_bert_config(cfg, batch, seq)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=bert.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": batch,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 1},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "steps_per_print": 100000})
    del params
    r = np.random.default_rng(0)
    data = mlm_batch(r, cfg.vocab_size, batch, seq)
    float(eng.train_batch(data)["loss"])
    ts = []
    for _ in range(steps):
        t0 = time.perf_counter()
        m = eng.train_batch(data)
        float(m["loss"])
        ts.append(time.perf_counter() - t0)
    ts.sort()
    dt = ts[len(ts) // 2]
    sps = batch / dt
    tflops = sps * flops_per_sample(cfg, seq) / 1e12
    del eng
    return dt, sps, tflops


def main():
    # each config runs in a FRESH subprocess: the remote compile helper on
    # this rig 500s on repeat compiles within one long-lived process
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        from deepspeed_tpu.utils.hbm import MemoryGuardError
        seq, batch, steps = (int(x) for x in sys.argv[2:5])
        try:
            dt, sps, tf = run(seq, batch, steps)
        except MemoryGuardError as e:
            print(json.dumps({"model": "bert-large", "seq": seq,
                              "batch": batch, "skipped": "memory guard",
                              "why": str(e)[:300]}), flush=True)
            return
        print(json.dumps({
            "model": "bert-large", "seq": seq, "batch": batch,
            "step_ms": round(dt * 1e3, 1),
            "samples_per_sec": round(sps, 1),
            "model_tflops": round(tf, 1),
            "ref_v100": {"128": "64 TFLOPS / 272 samples/s",
                         "512": "53 TFLOPS / 52 samples/s"}.get(str(seq)),
        }), flush=True)
        return
    import subprocess
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    from tools._subproc import run_json

    # per-config 1500s timeout: borderline-HBM compiles can grind >20min
    # on this rig (PERF.md) — report and keep going
    for seq, batch in [(128, 128), (128, 256), (128, 512),
                       (512, 16), (512, 32), (512, 64)]:
        run_json([sys.executable, __file__, "--one", str(seq), str(batch),
                  str(steps)], 1500, {"seq": seq, "batch": batch})


if __name__ == "__main__":
    main()
