#!/usr/bin/env bash
# Commit gate: the checks that must be green before any commit lands.
#
# Exists because round 3 shipped with a red suite (a lifted feature guard
# stranded the test that asserted the old behavior — VERDICT r3 weak #1).
# Run directly, or install as a pre-commit hook:
#
#   git config core.hooksPath .githooks     # one-time
#
# Modes:
#   tools/gate.sh            # full suite + driver entry points (~40min)
#   tools/gate.sh quick      # changed-path heuristic: changed test files
#                            # + test files matching changed modules +
#                            # the always-on smoke set (~minutes)
#   tools/gate.sh chaos      # fault-injection smoke: the chaos suite +
#                            # checkpoint crash recovery under a FIXED
#                            # seed (docs/ROBUSTNESS.md)
#
# NOTE: the gate tests the WORKING TREE. The pre-commit hook refuses
# partially-staged commits on gate-relevant paths (a green working tree
# says nothing about a staged subset of it).
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "chaos" ]]; then
    # deterministic chaos smoke: every injected failure path (transient
    # device errors, cache exhaustion, slow steps, crash-mid-checkpoint,
    # replica kills drained across a 3-replica router fleet) under a
    # pinned seed, so a red run is reproducible bit-for-bit
    echo "gate(chaos): fault-injection smoke (DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 python -m pytest tests/test_chaos.py \
        tests/test_checkpointing.py tests/test_router.py \
        tests/test_host_tier.py -q
    # tiered-KV three-site ambient injection: spill, restore and CRC
    # corruption all fire against the LIVE serving drives — every one
    # must degrade (blocks stay resident / cold-miss re-prefill), and
    # token parity must still hold (docs/KV_TIERING.md)
    echo "gate(chaos): host-tier three-site injection (DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 \
    DS_FAULTS="cache.spill:cache_exhausted@0;cache.restore:cache_exhausted@1;cache.host_corrupt:cache_exhausted@0" \
        python -m pytest tests/test_host_tier.py \
        -k "parity or drain_releases" -q
elif [[ "${1:-}" == "quick" ]]; then
    # lint the changed .py files PLUS their direct importers (--closure
    # quick mode, cached import graph from the last full run) so the
    # interprocedural rules (DS011-DS014) see cross-module breakage a
    # change introduces; whole-tree completeness checks are the full
    # gate's job. Falls back to a full two-phase pass (which seeds the
    # cache) when no cache exists yet.
    lint_changed=$(git diff --name-only --diff-filter=d HEAD -- \
                   'deepspeed_tpu/*.py' 'deepspeed_tpu/**/*.py' \
                   'tools/*.py' 'tools/**/*.py' \
                   'tests/*.py' 'tests/**/*.py' | tr '\n' ' ')
    if [[ -n "${lint_changed// }" ]]; then
        echo "gate(quick) dslint --closure: $lint_changed"
        mkdir -p build
        python -m tools.dslint --closure $lint_changed \
            --sarif build/dslint.sarif
    fi
    # changed TEST files run as-is; changed source files map to test
    # files by name heuristic; plus the always-on smoke set
    # (engine/config/gpt cover the load-bearing core; telemetry guards
    # the serving observability plane and its no-op contract)
    tests="tests/test_engine.py tests/test_config.py tests/test_gpt.py tests/test_telemetry.py tests/test_spec_serving.py tests/test_load_gen.py tests/test_autoscale.py"
    tests="$tests $(git diff --name-only --diff-filter=d HEAD -- 'tests/test_*.py' | tr '\n' ' ')"
    changed=$(git diff --name-only --diff-filter=d HEAD -- 'deepspeed_tpu/**.py' \
              | xargs -rn1 basename | sed 's/\.py$//')
    for c in $changed; do
        for t in tests/test_*"${c#*_}"* tests/test_*"$c"*; do
            [[ -f "$t" ]] && tests="$tests $t"
        done
    done
    tests=$(echo "$tests" | tr ' ' '\n' | sed '/^$/d' | sort -u | tr '\n' ' ')
    echo "gate(quick): $tests"
    python -m pytest $tests -q
else
    # full two-phase lint (per-file DS001-DS010 + interprocedural
    # DS011-DS014 over the package symbol table); also refreshes the
    # import-graph cache the quick gate's --closure mode reads and
    # leaves a SARIF log for CI viewers
    mkdir -p build
    python -m tools.dslint deepspeed_tpu tools tests \
        --stats --sarif build/dslint.sarif
    python -m pytest tests/ -q
    # shared-prefix cache knob smoke: the serving path must be green with
    # the prefix cache forced ON and forced OFF. The suite default leaves
    # DS_PREFIX_CACHE unset (= off), so without this loop the on-path only
    # gets coverage from tests that opt in explicitly (docs/PREFIX_CACHE.md)
    for pc in on off; do
        echo "gate: serving smoke (DS_PREFIX_CACHE=$pc)"
        DS_PREFIX_CACHE=$pc python -m pytest tests/test_serving.py \
            tests/test_prefix_cache.py -q
    done
    # telemetry knob smoke: the suite default leaves DS_TELEMETRY unset
    # (= off, the bit-reference no-op plane), so run the serving suites
    # once with tracing/metrics/breakdown forced ON — greedy parity and
    # the zero-recompile contract must hold either way
    # (docs/OBSERVABILITY.md)
    echo "gate: serving smoke (DS_TELEMETRY=on)"
    DS_TELEMETRY=on python -m pytest tests/test_serving.py \
        tests/test_telemetry.py tests/test_chaos.py -q
    # speculative-decode knob smoke: the suite default leaves
    # DS_SPEC_DECODE unset (= off, the plain-decode bit-reference), so
    # run the serving + chaos suites once with per-slot draft/verify
    # forced ON — greedy parity, eviction/requeue and the fault-degrade
    # path must hold with speculation active (docs/SPECULATIVE.md)
    echo "gate: serving smoke (DS_SPEC_DECODE=on)"
    DS_SPEC_DECODE=on python -m pytest tests/test_serving.py \
        tests/test_spec_serving.py tests/test_chaos.py -q
    # int8 KV-cache knob smoke: the suite default leaves DS_KV_QUANT
    # unset (= off, the bf16/fp32 bit-reference pool), so rerun the
    # serving, prefix-sharing and speculative suites once with the int8
    # paged pool forced ON — scheduling, COW/rollback bookkeeping and
    # the compile contract must hold on the quantized layout, and the
    # smoke-sized models stay greedy-argmax-stable under the rounding
    # (docs/KV_QUANT.md)
    echo "gate: serving smoke (DS_KV_QUANT=int8)"
    DS_KV_QUANT=int8 python -m pytest tests/test_serving.py \
        tests/test_prefix_cache.py tests/test_spec_serving.py \
        tests/test_kv_quant.py tests/test_kv_quant_serving.py -q
    # host-DRAM KV tier knob smoke: the suite default leaves
    # DS_KV_HOST_TIER unset (= off, the device-only bit-reference), so
    # rerun the serving + prefix-sharing + chaos suites once with the
    # tier forced ON (and the prefix cache it requires) — spill/restore
    # bookkeeping, every degrade path and the zero-recompile contract
    # must hold with the second tier active (docs/KV_TIERING.md)
    echo "gate: serving smoke (DS_KV_HOST_TIER=on)"
    DS_KV_HOST_TIER=on DS_PREFIX_CACHE=on python -m pytest \
        tests/test_serving.py tests/test_prefix_cache.py \
        tests/test_host_tier.py tests/test_chaos.py -q
    # sampled-mode smoke: the suites above exercise temperature=0
    # requests by default, so rerun the sampling + spec suites once
    # with speculation forced ON — this is the path where sampled
    # requests (temperature>0) flow through the rejection-sampling
    # verify instead of the greedy agreement rule, including the slow
    # end-to-end distribution-losslessness check (docs/SAMPLING.md)
    echo "gate: serving smoke (sampled, DS_SPEC_DECODE=on)"
    DS_SPEC_DECODE=on python -m pytest tests/test_sampling.py \
        tests/test_spec_serving.py -q
    # closed-loop smoke: the serve-autoscale CPU row must show the SLO
    # contrast (fixed fleet violates, policy fleet holds by scaling up)
    # and the chaos suite must stay green with the controller ACTIVE —
    # breaker drains and controller scale decisions compose
    # (docs/OBSERVABILITY.md)
    echo "gate: autoscale smoke (serve-autoscale-smoke + chaos with controller)"
    python - <<'PYEOF'
import json
from tools.infer_bench import bench_serving_autoscale_compare
res_f, res_p, policy = bench_serving_autoscale_compare("serve-autoscale-smoke")
assert res_f["ttft_p99"] > res_p["ttft_p99"], "no SLO contrast"
PYEOF
    DS_FAULT_SEED=0 python -m pytest tests/test_autoscale.py \
        tests/test_load_gen.py tests/test_router.py -q
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
fi
echo "gate: green"
