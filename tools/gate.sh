#!/usr/bin/env bash
# Commit gate: the checks that must be green before any commit lands.
#
# Exists because round 3 shipped with a red suite (a lifted feature guard
# stranded the test that asserted the old behavior — VERDICT r3 weak #1).
# Run directly, or install as a pre-commit hook:
#
#   git config core.hooksPath .githooks     # one-time
#
# Modes:
#   tools/gate.sh            # full suite + driver entry points (~40min)
#   tools/gate.sh quick      # changed-path heuristic: changed test files
#                            # + test files matching changed modules +
#                            # the always-on smoke set (~minutes)
#   tools/gate.sh chaos      # fault-injection smoke: the chaos suite +
#                            # checkpoint crash recovery under a FIXED
#                            # seed (docs/ROBUSTNESS.md)
#
# NOTE: the gate tests the WORKING TREE. The pre-commit hook refuses
# partially-staged commits on gate-relevant paths (a green working tree
# says nothing about a staged subset of it).
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "chaos" ]]; then
    # deterministic chaos smoke: every injected failure path (transient
    # device errors, cache exhaustion, slow steps, crash-mid-checkpoint,
    # replica kills drained across a 3-replica router fleet) under a
    # pinned seed, so a red run is reproducible bit-for-bit
    echo "gate(chaos): fault-injection smoke (DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 python -m pytest tests/test_chaos.py \
        tests/test_checkpointing.py tests/test_router.py \
        tests/test_host_tier.py tests/test_disagg.py -q
    # tiered-KV three-site ambient injection: spill, restore and CRC
    # corruption all fire against the LIVE serving drives — every one
    # must degrade (blocks stay resident / cold-miss re-prefill), and
    # token parity must still hold (docs/KV_TIERING.md)
    echo "gate(chaos): host-tier three-site injection (DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 \
    DS_FAULTS="cache.spill:cache_exhausted@0;cache.restore:cache_exhausted@1;cache.host_corrupt:cache_exhausted@0" \
        python -m pytest tests/test_host_tier.py \
        -k "parity or drain_releases" -q
    # KV-migration three-kind ambient injection over the mixed trace: a
    # transient gather failure, a REAL flipped host byte caught by the
    # CRC32 verify at landing, and a crash that breaks the destination
    # mid-scatter all fire against a live disaggregated fleet — every
    # one must degrade that request to a cold re-prefill on a decode
    # survivor, and tokens must stay bit-identical to the uninjected
    # fleet (docs/ROBUSTNESS.md migration ladder)
    echo "gate(chaos): KV-migration three-kind injection, mixed trace (ambient DS_FAULTS, DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 \
    DS_FAULTS="router.migrate_gather:device_error@0;router.migrate_corrupt:cache_exhausted@1;router.migrate_scatter:crash@2" \
        python - <<'PYEOF'
import jax, jax.numpy as jnp, numpy as np
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils.faults import FaultInjector
from tools.load_gen import _mk_serve_requests, make_requests

cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                    max_seq_len=96, use_flash_attention=False, remat=False,
                    dtype=jnp.float32)
params = gpt.init_params(jax.random.PRNGKey(0), cfg)
eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)

def mk_fleet(n):
    return [ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                          prefill_chunk=8, spec_decode=False)
            for _ in range(n)]

entries = make_requests(seed=0, mix="mixed", phases=[(40, 0.3)],
                        vocab_size=cfg.vocab_size, max_prompt_len=64)
# reference: the same disagg fleet under an EXPLICIT empty injector
# (the ambient DS_FAULTS install must not reach it)
ref = ReplicaRouter(mk_fleet(3), roles=["prefill", "decode", "decode"],
                    faults=FaultInjector([], seed=0)
                    ).run(_mk_serve_requests(entries))
# chaos fleet: faults=None picks up the ambient injector
router = ReplicaRouter(mk_fleet(3), roles=["prefill", "decode", "decode"])
res = router.run(_mk_serve_requests(entries))
assert set(res) == set(ref), "request set diverged"
for rid in ref:
    np.testing.assert_array_equal(res[rid], ref[rid])
assert router.stats["migration_fallbacks"] >= 3, router.stats
assert router.stats["breaker_trips"] >= 1, router.stats
print(f"gate(chaos): migration chaos ok "
      f"({router.stats['migrations']} migrated, "
      f"{router.stats['migration_fallbacks']} fell back cold)")
PYEOF
    # adapter-load injection against the AMBIENT injector install path
    # (the suite's own chaos test builds its injector explicitly): the
    # first acquire fails -> that request retires state="error" with the
    # pool untouched, the co-batched base request keeps parity, and the
    # same tenant loads cleanly once the window passes — degraded loads
    # never become wrong tokens (docs/ADAPTERS.md, docs/ROBUSTNESS.md)
    echo "gate(chaos): adapter-load injection (ambient DS_FAULTS, DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 DS_FAULTS="cache.adapter_load:cache_exhausted@0" \
    DS_LORA_SERVE=on python - <<'PYEOF'
import jax, jax.numpy as jnp, numpy as np
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.runtime.lora import add_lora, adapter_state_dict

cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                    max_seq_len=64, use_flash_attention=False, remat=False,
                    dtype=jnp.float32)
params = gpt.init_params(jax.random.PRNGKey(0), cfg)
eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
p1, p2 = (np.arange(3, 11, dtype=np.int32), np.arange(20, 27, dtype=np.int32))
ref = eng.generate(p2[None], max_new_tokens=5)[0]
srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                    lora_pool_blocks=2, lora_max_rank=4, lora_rank_block=4)
srv.register_adapter("t1", adapter_state_dict(
    add_lora(params, rng=jax.random.PRNGKey(1), rank=4, alpha=8.0)))
bad = ServeRequest(rid="bad", prompt=p1, max_new_tokens=5, adapter_id="t1")
ok = ServeRequest(rid="ok", prompt=p2, max_new_tokens=5)
out = srv.run([bad, ok])
assert bad.state == "error" and ok.state == "done", (bad.state, ok.state)
np.testing.assert_array_equal(out["ok"], ref)
assert srv.adapters.stats()["resident"] == 0, "failed load leaked pool state"
retry = ServeRequest(rid="r", prompt=p1, max_new_tokens=5, adapter_id="t1")
srv.run([retry])
assert retry.state == "done", retry.state
print("gate(chaos): adapter-load degrade ok")
PYEOF
    # fused-horizon injection: a serving.horizon device_error fires
    # BEFORE any capacity or slot state moves and degrades that step to
    # plain N=1 single-step decode (stats["horizon_fallbacks"]) — the
    # run still drains and streams stay bit-identical to the N=1
    # reference (docs/MULTISTEP.md, docs/ROBUSTNESS.md)
    echo "gate(chaos): horizon degrade injection (ambient DS_FAULTS, DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 DS_FAULTS="serving.horizon:device_error@1*3" \
    DS_DECODE_HORIZON=8 python -m pytest tests/test_horizon.py \
        -k "degrade or parity" -q
    # flight-recorder postmortem under injected watchdog degrade: the
    # chaos-induced DegradedError must leave a versioned, CRC-valid
    # artifact behind, and the stdlib reader (tools/postmortem.py) must
    # reconstruct the fired faults and a conserved cost summary from
    # the file alone (docs/OBSERVABILITY.md, docs/ROBUSTNESS.md)
    echo "gate(chaos): watchdog degrade -> postmortem artifact (DS_FAULT_SEED=0)"
    DS_FAULT_SEED=0 DS_TELEMETRY=on DS_FLIGHT_RECORDER=on \
    DS_FLIGHT_DIR=/tmp/ds_gate_flight python - <<'PYEOF'
import glob, os, jax, jax.numpy as jnp, numpy as np
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine)
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault

cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                    max_seq_len=64, use_flash_attention=False, remat=False,
                    dtype=jnp.float32)
params = gpt.init_params(jax.random.PRNGKey(0), cfg)
eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
r = np.random.default_rng(12)
with faults_lib.injected(
        Fault("serving.decode", "slow", step=4, count=2, param=0.05),
        seed=0) as inj:
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        step_time_budget_s=0.01, watchdog_grace=2,
                        spec_decode=False, decode_horizon=1)
    try:
        srv.run([ServeRequest(rid="a", prompt=r.integers(1, 128, 6).astype(np.int32),
                              max_new_tokens=12),
                 ServeRequest(rid="b", prompt=r.integers(1, 128, 9).astype(np.int32),
                              max_new_tokens=3)])
        raise SystemExit("watchdog never tripped")
    except DegradedError:
        pass
assert srv.flight.dumps, "degrade wrote no postmortem artifact"
path = srv.flight.dumps[-1]
from tools.postmortem import analyze_postmortem, load_artifact
summary = analyze_postmortem(load_artifact(path))   # CRC + version gate
assert "over budget" in summary["incident"]["reason"]
assert [tuple(f) for f in summary["faults"]] == inj.fired
live = srv.costs.snapshot()
assert summary["totals"]["per_class"] == live["totals"]
assert summary["totals"]["flops_total"] == live["flops_total"] > 0
print(f"gate(chaos): postmortem artifact ok ({os.path.basename(path)})")
PYEOF
elif [[ "${1:-}" == "quick" ]]; then
    # lint the changed .py files PLUS their direct importers (--closure
    # quick mode, cached import graph from the last full run) so the
    # interprocedural rules (DS011-DS014) and the flow-sensitive v3
    # rules (DS015-DS018: jit-twin drift, resource pairing, traced
    # escape, snapshot round-trip) see cross-module breakage a change
    # introduces; whole-tree completeness checks are the full gate's
    # job. Falls back to a full two-phase pass (which seeds the cache)
    # when no cache exists yet — also when jit_registry.py or
    # telemetry_schema.json changed, since their content hashes key the
    # cache.
    lint_changed=$(git diff --name-only --diff-filter=d HEAD -- \
                   'deepspeed_tpu/*.py' 'deepspeed_tpu/**/*.py' \
                   'tools/*.py' 'tools/**/*.py' \
                   'tests/*.py' 'tests/**/*.py' | tr '\n' ' ')
    if [[ -n "${lint_changed// }" ]]; then
        echo "gate(quick) dslint --closure: $lint_changed"
        mkdir -p build
        python -m tools.dslint --closure $lint_changed \
            --sarif build/dslint.sarif
    fi
    # changed TEST files run as-is; changed source files map to test
    # files by name heuristic; plus the always-on smoke set
    # (engine/config/gpt cover the load-bearing core; telemetry guards
    # the serving observability plane and its no-op contract)
    tests="tests/test_engine.py tests/test_config.py tests/test_gpt.py tests/test_telemetry.py tests/test_spec_serving.py tests/test_load_gen.py tests/test_autoscale.py"
    tests="$tests $(git diff --name-only --diff-filter=d HEAD -- 'tests/test_*.py' | tr '\n' ' ')"
    changed=$(git diff --name-only --diff-filter=d HEAD -- 'deepspeed_tpu/**.py' \
              | xargs -rn1 basename | sed 's/\.py$//')
    for c in $changed; do
        for t in tests/test_*"${c#*_}"* tests/test_*"$c"*; do
            [[ -f "$t" ]] && tests="$tests $t"
        done
    done
    tests=$(echo "$tests" | tr ' ' '\n' | sed '/^$/d' | sort -u | tr '\n' ' ')
    echo "gate(quick): $tests"
    python -m pytest $tests -q
else
    # full two-phase lint (per-file DS001-DS010 + interprocedural
    # DS011-DS014 over the package symbol table); also refreshes the
    # import-graph cache the quick gate's --closure mode reads and
    # leaves a SARIF log for CI viewers
    mkdir -p build
    python -m tools.dslint deepspeed_tpu tools tests \
        --stats --sarif build/dslint.sarif
    python -m pytest tests/ -q
    # shared-prefix cache knob smoke: the serving path must be green with
    # the prefix cache forced ON and forced OFF. The suite default leaves
    # DS_PREFIX_CACHE unset (= off), so without this loop the on-path only
    # gets coverage from tests that opt in explicitly (docs/PREFIX_CACHE.md)
    for pc in on off; do
        echo "gate: serving smoke (DS_PREFIX_CACHE=$pc)"
        DS_PREFIX_CACHE=$pc python -m pytest tests/test_serving.py \
            tests/test_prefix_cache.py -q
    done
    # telemetry knob smoke: the suite default leaves DS_TELEMETRY unset
    # (= off, the bit-reference no-op plane), so run the serving suites
    # once with tracing/metrics/breakdown forced ON — greedy parity and
    # the zero-recompile contract must hold either way
    # (docs/OBSERVABILITY.md)
    echo "gate: serving smoke (DS_TELEMETRY=on)"
    DS_TELEMETRY=on python -m pytest tests/test_serving.py \
        tests/test_telemetry.py tests/test_chaos.py -q
    # speculative-decode knob smoke: the suite default leaves
    # DS_SPEC_DECODE unset (= off, the plain-decode bit-reference), so
    # run the serving + chaos suites once with per-slot draft/verify
    # forced ON — greedy parity, eviction/requeue and the fault-degrade
    # path must hold with speculation active (docs/SPECULATIVE.md)
    echo "gate: serving smoke (DS_SPEC_DECODE=on)"
    DS_SPEC_DECODE=on python -m pytest tests/test_serving.py \
        tests/test_spec_serving.py tests/test_chaos.py -q
    # int8 KV-cache knob smoke: the suite default leaves DS_KV_QUANT
    # unset (= off, the bf16/fp32 bit-reference pool), so rerun the
    # serving, prefix-sharing and speculative suites once with the int8
    # paged pool forced ON — scheduling, COW/rollback bookkeeping and
    # the compile contract must hold on the quantized layout, and the
    # smoke-sized models stay greedy-argmax-stable under the rounding
    # (docs/KV_QUANT.md)
    echo "gate: serving smoke (DS_KV_QUANT=int8)"
    DS_KV_QUANT=int8 python -m pytest tests/test_serving.py \
        tests/test_prefix_cache.py tests/test_spec_serving.py \
        tests/test_kv_quant.py tests/test_kv_quant_serving.py -q
    # host-DRAM KV tier knob smoke: the suite default leaves
    # DS_KV_HOST_TIER unset (= off, the device-only bit-reference), so
    # rerun the serving + prefix-sharing + chaos suites once with the
    # tier forced ON (and the prefix cache it requires) — spill/restore
    # bookkeeping, every degrade path and the zero-recompile contract
    # must hold with the second tier active (docs/KV_TIERING.md)
    echo "gate: serving smoke (DS_KV_HOST_TIER=on)"
    DS_KV_HOST_TIER=on DS_PREFIX_CACHE=on python -m pytest \
        tests/test_serving.py tests/test_prefix_cache.py \
        tests/test_host_tier.py tests/test_chaos.py -q
    # multi-tenant LoRA knob smoke: the suite default leaves
    # DS_LORA_SERVE unset (= off, the base-only bit-reference with zero
    # lora programs), so rerun the serving + spec + prefix suites once
    # with the adapter subsystem forced ON — base-only traffic must
    # stay bit-identical through the _l twins' zero trash-block row,
    # and the compile contract must hold on the lora program set
    # (docs/ADAPTERS.md)
    echo "gate: serving smoke (DS_LORA_SERVE=on)"
    DS_LORA_SERVE=on python -m pytest tests/test_serving.py \
        tests/test_spec_serving.py tests/test_prefix_cache.py \
        tests/test_adapter_serving.py -q
    # sampled-mode smoke: the suites above exercise temperature=0
    # requests by default, so rerun the sampling + spec suites once
    # with speculation forced ON — this is the path where sampled
    # requests (temperature>0) flow through the rejection-sampling
    # verify instead of the greedy agreement rule, including the slow
    # end-to-end distribution-losslessness check (docs/SAMPLING.md)
    echo "gate: serving smoke (sampled, DS_SPEC_DECODE=on)"
    DS_SPEC_DECODE=on python -m pytest tests/test_sampling.py \
        tests/test_spec_serving.py -q
    # fused multi-step decode knob smoke: the suite default leaves
    # DS_DECODE_HORIZON unset (= 1, the one-token-per-dispatch
    # bit-reference), so rerun the serving + sampling + chaos suites
    # once with an 8-iteration fused horizon forced ON — greedy AND
    # sampled parity, stop/eviction/requeue bookkeeping, deadlines and
    # every degrade path must hold when the scheduler host loop only
    # runs at horizon boundaries (docs/MULTISTEP.md)
    echo "gate: serving smoke (DS_DECODE_HORIZON=8)"
    DS_DECODE_HORIZON=8 python -m pytest tests/test_serving.py \
        tests/test_sampling.py tests/test_horizon.py tests/test_chaos.py -q
    # cost-accounting + flight-recorder smoke: the suite default leaves
    # DS_TELEMETRY and DS_COST_ACCOUNTING unset (= off, the no-op
    # accountant), so run the conservation + postmortem suite once with
    # the telemetry plane forced ON — per-request/tenant attribution
    # must balance against the global counters to the integer in every
    # scenario (eviction, spec fallback, horizon, router drain), and
    # the DegradedError postmortem round-trip must hold
    # (docs/OBSERVABILITY.md)
    echo "gate: cost accounting conservation + postmortem (DS_TELEMETRY=on)"
    DS_TELEMETRY=on python -m pytest tests/test_cost_accounting.py -q
    # and once with the standalone knob: cost accounting without the
    # rest of the telemetry plane must still conserve
    echo "gate: cost accounting standalone (DS_COST_ACCOUNTING=on)"
    DS_COST_ACCOUNTING=on python -m pytest tests/test_cost_accounting.py \
        -k "knob or snapshot or analytic" -q
    # closed-loop smoke: the serve-autoscale CPU row must show the SLO
    # contrast (fixed fleet violates, policy fleet holds by scaling up)
    # and the chaos suite must stay green with the controller ACTIVE —
    # breaker drains and controller scale decisions compose
    # (docs/OBSERVABILITY.md)
    echo "gate: autoscale smoke (serve-autoscale-smoke + chaos with controller)"
    python - <<'PYEOF'
import json
from tools.infer_bench import bench_serving_autoscale_compare
res_f, res_p, policy = bench_serving_autoscale_compare("serve-autoscale-smoke")
assert res_f["ttft_p99"] > res_p["ttft_p99"], "no SLO contrast"
PYEOF
    DS_FAULT_SEED=0 python -m pytest tests/test_autoscale.py \
        tests/test_load_gen.py tests/test_router.py -q
    # disaggregation smoke: at the same chip count, the monolithic
    # fleet must violate at least one per-kind SLO on the mixed
    # rag+chat trace while the prefill/decode split holds ALL of them,
    # with bit-identical tokens and zero steady-state compiles — the
    # bench-row contract from docs/ROBUSTNESS.md
    echo "gate: disagg smoke (serve-disagg-smoke SLO contrast)"
    python - <<'PYEOF'
from tools.infer_bench import SERVE_COMPARE_CONFIGS, bench_serving_disagg_compare
kw = dict(next(kw for name, kw in SERVE_COMPARE_CONFIGS
               if name == "serve-disagg-smoke"))
kw.pop("mode", None)
row, _, _, _ = bench_serving_disagg_compare("serve-disagg-smoke", **kw)
assert row["slo_violated_mono"], "monolithic fleet never violated an SLO"
assert row["slo_holds_disagg"], f"disagg fleet violated: {row}"
assert row["output_identical"], "tokens diverged between fleets"
assert row["steady_state_compiles"] == 0, row["steady_state_compiles"]
PYEOF
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
fi
echo "gate: green"
