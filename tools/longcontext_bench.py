"""Long-context benchmark: flash on one chip, ring vs Ulysses on a mesh.

The reference's long-sequence story is block-sparse attention (README
claims 10x longer sequences, ref README.md:38); this framework's is exact
attention — the Pallas flash kernel at long S on one chip, and
sequence-parallel attention (ring / Ulysses) over the mesh. This tool
measures both:

  python tools/longcontext_bench.py chip   # real-TPU: GPT train step at 2k-16k
  python tools/longcontext_bench.py mesh   # 8-dev CPU mesh: ring vs ulysses

"chip" runs each sequence length in a fresh subprocess and prints one JSON
line per config (attention-flops MFU rises with S — attention dominates).
"mesh" checks ring/Ulysses parity against dense attention and prints step
times (CPU wall times are indicative only; the point is the collective
program compiles and the math matches).
"""

import json
import sys

sys.path.insert(0, ".")

CHIP_CODE = """
import sys, json, time
sys.path.insert(0, '.')
import jax, numpy as np, jax.numpy as jnp
from bench import run_config, peak_flops
from deepspeed_tpu.models import gpt

seq = {seq}
batch = {batch}
dt, tps, mfu = run_config('gpt2-small', batch, seq, 6,
    {{'zero_optimization': {{'stage': 1}}}}, True,
    flash_block=1024, remat_pol='{pol}', loss_chunk=2048)
print(json.dumps({{'config': 'gpt2-small', 'seq': seq, 'batch': batch,
    'remat': '{pol}',
    'step_ms': round(dt*1e3, 1), 'tokens_per_s': round(tps, 1),
    'mfu': round(mfu, 4)}}))
"""


def chip():
    from tools._subproc import run_json

    # tokens/step held ~constant: long S trades batch. 1500s/config
    # (matching the other bench tools, and 3x1500 fits chip_queue's
    # 4800s item budget): on this rig a compile that runs longer is in
    # the borderline-HBM grind and will not produce a number anyway
    # (PERF.md).
    grid = [(8, 2048, "selective"), (2, 8192, "selective"),
            (1, 16384, "full")]
    for batch, seq, pol in grid:
        run_json([sys.executable, "-c",
                  CHIP_CODE.format(seq=seq, batch=batch, pol=pol)],
                 1500, {"seq": seq, "batch": batch})


def mesh():
    import os
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import time

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.ops.attention.ring import ring_attention
    from deepspeed_tpu.ops.attention.ulysses import ulysses_attention

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("sequence",))
    B, S, H, D = 1, 4096, 8, 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, S, H, D)),
                           jnp.float32) * 0.3 for _ in range(3))
    sh = NamedSharding(mesh, P(None, "sequence", None, None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))

    from deepspeed_tpu.ops.attention.ring import zigzag_perm, zigzag_unperm

    dense = mha_reference(q, k, v, causal=True)
    zp, zip_ = zigzag_perm(S, 8), zigzag_unperm(S, 8)
    qz, kz, vz = (jax.device_put(t[:, zp], sh) for t in (q, k, v))
    for name, fn in (("ring", ring_attention),
                     ("ring-zigzag", ring_attention),
                     ("ulysses", ulysses_attention)):
        zig = name == "ring-zigzag"
        kw = {"layout": "zigzag"} if zig else {}
        f = jax.jit(lambda a, b, c, fn=fn, kw=kw: fn(  # dslint: disable=DS002 — bench re-jits per (impl, seqlen) config on purpose
            a, b, c, mesh=mesh, axis="sequence", causal=True, **kw))
        args = (qz, kz, vz) if zig else (qs, ks, vs)
        out = jax.block_until_ready(f(*args))
        if zig:
            out = out[:, zip_]
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - dense)))
        t0 = time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(f(*args))
        dt = (time.perf_counter() - t0) / 3
        print(json.dumps({"impl": name, "seq": S, "sp": 8,
                          "max_err_vs_dense": round(err, 6),
                          "step_ms_cpu": round(dt * 1e3, 1)}), flush=True)

    # memory curve: XLA temp-buffer bytes of the compiled fwd+bwd program.
    # The ring's chunked local block holds O(S_loc*chunk) score memory, so
    # its temps grow LINEARLY with S; dense attention grows O(S^2). This
    # is the capacity claim the reference's block-sparse attention makes
    # (ref README.md:38 "10x longer sequences") — here with EXACT
    # attention.
    def temp_bytes(fun, *args):
        comp = jax.jit(fun).lower(*args).compile()
        m = comp.memory_analysis()
        return None if m is None else int(m.temp_size_in_bytes)

    chunk = 512
    for S_curve in (2048, 4096, 8192, 16384):
        qc, kc, vc = (jnp.zeros((1, S_curve, H, D), jnp.float32)
                      for _ in range(3))
        shc = NamedSharding(mesh, P(None, "sequence", None, None))
        qc, kc, vc = (jax.device_put(t, shc) for t in (qc, kc, vc))

        def ring_loss(a, b, c):
            return (ring_attention(a, b, c, mesh=mesh, axis="sequence",
                                   causal=True, chunk=chunk) ** 2).sum()

        def dense_loss(a, b, c):
            return (mha_reference(a, b, c, causal=True) ** 2).sum()

        ring_t = temp_bytes(jax.grad(ring_loss, argnums=(0, 1, 2)),
                            qc, kc, vc)
        dense_t = (temp_bytes(jax.grad(dense_loss, argnums=(0, 1, 2)),
                              qc, kc, vc) if S_curve <= 8192 else None)
        print(json.dumps({
            "metric": "longcontext_memory_curve", "seq": S_curve,
            "sp": 8, "chunk": chunk,
            "ring_temp_mb": (None if ring_t is None
                             else round(ring_t / 1e6, 1)),
            "dense_temp_mb": (None if dense_t is None
                              else round(dense_t / 1e6, 1)),
        }), flush=True)


if __name__ == "__main__":
    (chip if (sys.argv[1:] or ["mesh"])[0] == "chip" else mesh)()
