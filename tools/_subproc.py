"""Shared helper for the on-chip bench tools: run one measurement config
in a subprocess with a timeout and print exactly one JSON line."""

import json
import subprocess
import sys


def run_json(cmd, timeout, tag):
    """Run cmd; print its last JSON stdout line, or a {**tag, ...} error
    line on failure/timeout. Never raises."""
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        print(json.dumps({**tag, "timeout_s": timeout}), flush=True)
        return
    line = next((ln for ln in reversed(r.stdout.splitlines())
                 if ln.startswith("{")), None)
    print(line or json.dumps({**tag, "rc": r.returncode,
                              "err": r.stderr[-300:]}), flush=True)
