"""Background rig watcher: poll the TPU tunnel until it recovers, then
fire the phase-1 on-chip measurement queue once and exit.

The round-2 outage (STATUS.md) showed a wedged tunnel can eat a whole
round: every recovery minute matters, and a human (or the main build
session) shouldn't have to poll. Run this with output redirected to a
log; it exits 0 after the queue completes, 2 on deadline with the rig
still down — either way the exit itself is the notification.

Usage: python tools/rig_watch.py [--deadline-hours H] [item ...]
Items are chip_queue names; default is the phase-1 set (smoke + probes +
trace) — fast enough to leave chip time for targeted follow-ups.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, ".")

from tools.chip_queue import healthy  # noqa: E402

PHASE1 = ["flash-smoke", "probe", "trace-1.5b"]
# cadence is env-overridable so the recovery cycle can be REHEARSED on
# the CPU backend (tests/test_rig_recovery.py) at second-scale timings —
# the automation gets a test before its one shot at the real rig
POLL_S = int(os.environ.get("DS_RIGWATCH_POLL_S", 300))  # dslint: disable=DS005 — standalone watchdog, env IS its config
CONFIRM_S = int(os.environ.get("DS_RIGWATCH_CONFIRM_S", 45))  # dslint: disable=DS005 — standalone watchdog, env IS its config


def log(**kw):
    print(json.dumps({"t": round(time.time()), **kw}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-hours", type=float, default=10.0)
    ap.add_argument("--results", default="chipq_results.log",
                    help="queue output file (rehearsal uses a tmp path)")
    ap.add_argument("--pick-out", default=None,
                    help="override pick_headline's BENCH_HEADLINE.json "
                         "target (rehearsal only)")
    ap.add_argument("items", nargs="*", default=None)
    args = ap.parse_args()
    items = args.items or PHASE1
    deadline = time.time() + args.deadline_hours * 3600

    n = 0
    while time.time() < deadline:
        n += 1
        if healthy(timeout=150):
            # require a second green probe: the tunnel flaps on the way
            # back up, and a half-recovered backend wedges mid-queue
            time.sleep(CONFIRM_S)
            if healthy(timeout=150):
                log(event="rig healthy", probes=n)
                break
            log(event="flapped", probes=n)
        else:
            log(event="still down", probes=n)
        time.sleep(POLL_S)
    else:
        log(event="deadline, rig never recovered", probes=n)
        sys.exit(2)

    t0 = time.time()
    log(event="queue start", items=items)
    # the queue writes the results file DIRECTLY as its stdout (fresh
    # per run): the measurements survive a dead watcher, and the
    # unattended headline decision below reads only this run's lines
    results_path = args.results
    with open(results_path, "w") as res:
        rc = subprocess.run(
            [sys.executable, "tools/chip_queue.py"] + items,
            stdout=res, stderr=subprocess.STDOUT).returncode
    log(event="queue done", rc=rc, results=results_path,
        minutes=round((time.time() - t0) / 60, 1))
    if any("probe" in it for it in items):
        cmd = [sys.executable, "tools/pick_headline.py",
               results_path, "--apply"]
        if args.pick_out:
            cmd += ["--out", args.pick_out]
        d = subprocess.run(cmd, capture_output=True, text=True)
        log(event="headline decision", out=d.stdout.strip()[-400:],
            err=d.stderr.strip()[-400:], rc=d.returncode)


if __name__ == "__main__":
    main()
