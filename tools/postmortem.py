#!/usr/bin/env python
"""Postmortem reader — reconstruct a serving incident from a
flight-recorder artifact alone.

Reads the versioned, CRC-stamped JSON that
``deepspeed_tpu/telemetry/flight.py`` writes on ``DegradedError`` /
watchdog trip / breaker break (or an explicit ``dump()``), and rebuilds:

- the **request timeline** from the tracer ring (per-rid lifecycle:
  enqueue -> admit -> prefill -> decode -> ... -> finish, with relative
  timestamps),
- the **fired faults** and **autoscaler decisions** leading up to the
  incident,
- the **per-tenant / per-class cost summary** (FLOPs, HBM bytes,
  dispatches, KV block-seconds) from the cost-accounting section,
- the resolved flags and jax/platform identity of the process that died.

Deliberately **stdlib-only**: this tool must run on a machine with no
jax, no numpy, and no live serving objects — only the artifact file.
The verification logic therefore mirrors (rather than imports)
``deepspeed_tpu.telemetry.flight``: same canonical serialization, same
CRC recomputation, same version gate. Keep the two in sync.

Usage::

    python tools/postmortem.py <artifact.json>          # human report
    python tools/postmortem.py <artifact.json> --json   # stable schema
"""

import json
import sys
import zlib

#: must match deepspeed_tpu.telemetry.flight.ARTIFACT_VERSION
ARTIFACT_VERSION = 1

#: tracer event types that mark lifecycle phase edges, in display order
_LIFECYCLE = ("enqueue", "admit", "prefill_chunk", "prefix_hit",
              "decode", "spec_step", "evict", "requeue", "retry",
              "timeout", "stop_hit", "finish", "degraded", "fault")


def canonical_json(body):
    """Same canonical form the recorder CRC-stamps: sorted keys, no
    whitespace."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def verify_artifact(artifact):
    """Raise ValueError on unknown version or CRC mismatch."""
    if not isinstance(artifact, dict) or "body" not in artifact:
        raise ValueError("not a flight-recorder artifact (no body)")
    ver = artifact.get("version")
    if ver != ARTIFACT_VERSION:
        raise ValueError(f"unknown postmortem artifact version {ver!r} "
                         f"(reader knows {ARTIFACT_VERSION})")
    want = artifact.get("crc32")
    got = zlib.crc32(canonical_json(artifact["body"]).encode("utf-8"))
    if want != got:
        raise ValueError(f"postmortem CRC mismatch: stamped {want}, "
                         f"recomputed {got} — artifact corrupt")


def load_artifact(path):
    with open(path, "r", encoding="utf-8") as f:
        artifact = json.load(f)
    verify_artifact(artifact)
    return artifact["body"]


# ---------------------------------------------------------------------------
# reconstruction
# ---------------------------------------------------------------------------

def _request_timeline(records):
    """Group tracer records by rid into ordered event lists with
    timestamps relative to the oldest record in the ring."""
    if not records:
        return {}, 0.0
    t0 = min(r[0] for r in records)
    per_rid = {}
    for rec in records:
        ts, etype, rid, step, slot = rec[0], rec[1], rec[2], rec[3], rec[4]
        data = rec[5] if len(rec) > 5 else None
        key = rid if rid is not None else "<system>"
        per_rid.setdefault(key, []).append({
            "t": round(ts - t0, 6), "event": etype, "step": step,
            "slot": slot, "data": data,
        })
    return per_rid, t0


def _sum_footprint(fp):
    """Total flops/bytes/dispatches across dispatch classes of one
    footprint dict (tolerates the block_seconds scalar key)."""
    out = {"flops": 0, "hbm_bytes": 0, "dispatches": 0,
           "block_seconds": 0}
    for key, val in fp.items():
        if key == "block_seconds":
            out["block_seconds"] += int(val)
        elif isinstance(val, dict):
            for k in ("flops", "hbm_bytes", "dispatches"):
                out[k] += int(val.get(k, 0))
    return out


def analyze_postmortem(body, quiet=True):
    """Pure reconstruction: artifact body dict -> stable summary dict.

    The summary is what ``--json`` prints and what the round-trip test
    compares against live objects, so keep the schema stable: top-level
    keys ``incident``, ``identity``, ``requests``, ``faults``,
    ``autoscale``, ``tenants``, ``totals``, ``flags``, ``programs``.
    """
    records = body.get("tracer") or []
    per_rid, _ = _request_timeline(records)

    requests = {}
    rows = body.get("requests")
    if isinstance(rows, list):
        for row in rows:
            if isinstance(row, dict) and "rid" in row:
                requests[row["rid"]] = row
    # merge the tracer-derived timeline into (or create) each request row
    summary_requests = {}
    for rid in sorted(set(per_rid) | set(requests)):
        row = dict(requests.get(rid, {}))
        events = per_rid.get(rid, [])
        row["events"] = events
        row["event_counts"] = {}
        for ev in events:
            row["event_counts"][ev["event"]] = \
                row["event_counts"].get(ev["event"], 0) + 1
        if "cost" in row and isinstance(row["cost"], dict):
            row["cost_total"] = _sum_footprint(row["cost"])
        summary_requests[rid] = row

    costs = body.get("costs") or {}
    tenants = {}
    for tid, fp in sorted((costs.get("tenants") or {}).items()):
        tenants[tid] = {"footprint": fp, "total": _sum_footprint(fp)}

    programs = body.get("programs") or {}
    if isinstance(programs, dict) and "programs" in programs:
        programs = programs["programs"]

    summary = {
        "incident": {
            "label": body.get("label"),
            "reason": body.get("reason"),
            "wall_time": body.get("wall_time"),
            "schema": body.get("schema"),
        },
        "identity": body.get("identity") or {},
        "requests": summary_requests,
        "faults": body.get("faults") or [],
        "autoscale": body.get("autoscale") or [],
        "tenants": tenants,
        "totals": {
            "per_class": costs.get("totals") or {},
            "flops_total": int(costs.get("flops_total") or 0),
            "hbm_bytes_total": int(costs.get("hbm_bytes_total") or 0),
            "block_seconds_total": int(costs.get("block_seconds_total")
                                       or 0),
        },
        "flags": body.get("flags") or {},
        "programs": {"count": len(programs),
                     "ids": sorted(programs)},
    }
    if not quiet:
        print(format_report(summary))
    return summary


def format_report(summary):
    """Human-readable incident report."""
    lines = []
    inc = summary["incident"]
    ident = summary["identity"]
    lines.append(f"== postmortem: {inc.get('label')} ==")
    lines.append(f"reason      : {inc.get('reason')}")
    lines.append(f"wall_time   : {inc.get('wall_time')}")
    lines.append(f"identity    : python {ident.get('python')} / "
                 f"jax {ident.get('jax')} / backend "
                 f"{ident.get('backend', '?')} "
                 f"({ident.get('device_kind', '?')})")
    lines.append(f"programs    : {summary['programs']['count']} in cost "
                 f"registry")

    lines.append("")
    lines.append(f"-- requests ({len(summary['requests'])}) --")
    for rid, row in summary["requests"].items():
        counts = " ".join(f"{k}x{v}" for k, v in
                          sorted(row.get("event_counts", {}).items()))
        state = row.get("state", "?")
        lines.append(f"  {rid:<16} state={state:<9} "
                     f"gen={row.get('generated', '?'):<4} {counts}")
        tot = row.get("cost_total")
        if tot:
            lines.append(f"  {'':<16} cost: {tot['flops']} flops, "
                         f"{tot['hbm_bytes']} hbm bytes, "
                         f"{tot['dispatches']} dispatches, "
                         f"{tot['block_seconds']} block-seconds")
        for ev in row.get("events", []):
            data = "" if ev["data"] is None else f" {ev['data']}"
            lines.append(f"    +{ev['t']:.4f}s step={ev['step']} "
                         f"slot={ev['slot']} {ev['event']}{data}")

    if summary["faults"]:
        lines.append("")
        lines.append(f"-- fired faults ({len(summary['faults'])}) --")
        for f in summary["faults"]:
            lines.append(f"  {f}")

    if summary["autoscale"]:
        lines.append("")
        lines.append(f"-- autoscaler decisions "
                     f"({len(summary['autoscale'])}) --")
        for d in summary["autoscale"]:
            lines.append(f"  {d}")

    lines.append("")
    lines.append("-- cost summary --")
    tot = summary["totals"]
    lines.append(f"  global: {tot['flops_total']} flops, "
                 f"{tot['hbm_bytes_total']} hbm bytes, "
                 f"{tot['block_seconds_total']} kv block-seconds")
    for cls, c in sorted(tot["per_class"].items()):
        lines.append(f"    {cls:<8} {c.get('dispatches', 0):>8} dispatches "
                     f"{c.get('flops', 0):>16} flops "
                     f"{c.get('hbm_bytes', 0):>16} bytes")
    for tid, t in summary["tenants"].items():
        tt = t["total"]
        lines.append(f"  tenant {tid:<12} {tt['flops']} flops, "
                     f"{tt['hbm_bytes']} hbm bytes, "
                     f"{tt['block_seconds']} block-seconds")
    return "\n".join(lines)


def main(argv):
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    path = argv[0]
    as_json = "--json" in argv[1:]
    try:
        body = load_artifact(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"postmortem: cannot read {path}: {e}", file=sys.stderr)
        return 2
    summary = analyze_postmortem(body)
    try:
        if as_json:
            print(json.dumps(summary, indent=2, sort_keys=True))
        else:
            print(format_report(summary))
    except BrokenPipeError:
        # downstream pager/head closed the pipe — normal CLI exit
        sys.stderr.close()
        return 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
