"""MoE GPT training-step benchmark: dispatch overhead vs dense.

Measures the GPT-MoE NLG workload (ref capability: BASELINE.json config #5)
on the local chip: a dense GPT layer stack vs the same stack with GShard
MoE FFNs (top-1 / top-2), same d_model — reporting step time and the MoE
dispatch overhead ratio. Each config runs in a fresh subprocess.

Usage: python tools/moe_bench.py [steps]
"""

import sys

sys.path.insert(0, ".")

CODE = """
import sys, json, time
sys.path.insert(0, '.')
import jax, numpy as np, jax.numpy as jnp
import deepspeed_tpu

kind = {kind!r}
batch, seq, steps = {batch}, {seq}, {steps}
on_tpu = 'tpu' in (jax.devices()[0].platform + jax.devices()[0].device_kind).lower()

if kind == 'dense':
    from deepspeed_tpu.models import gpt as M
    # match the MoE path's cost model: moe_gpt remats with
    # nothing_saveable (full) and uses the dense CE — keep both equal so
    # the ratio isolates DISPATCH cost, not remat/CE differences
    cfg = M.preset('gpt2-small', max_seq_len=seq, dtype=jnp.bfloat16,
                   remat=True, remat_policy='full', use_flash_attention=on_tpu,
                   loss_chunk=0)
else:
    from deepspeed_tpu.models import moe_gpt as M
    cfg = M.MoEGPTConfig(n_layers=12, n_heads=12, d_model=768,
                         max_seq_len=seq, dtype=jnp.bfloat16, remat=True,
                         use_flash_attention=on_tpu,
                         num_experts={experts}, moe_k={k},
                         capacity_factor=1.25)
if on_tpu:
    # refuse borderline-HBM compiles before any backend contact
    # (utils/hbm.py, PERF.md incident log)
    from deepspeed_tpu.utils import hbm
    try:
        if kind == 'dense':
            hbm.guard_gpt_config(cfg, batch, seq)
        else:
            hbm.guard_moe_config(cfg, batch, seq)
    except hbm.MemoryGuardError as e:
        print(json.dumps({{"kind": kind, "experts": {experts},
            "skipped": "memory guard", "why": str(e)[:300]}}))
        sys.exit(0)
params = M.init_params(jax.random.PRNGKey(0), cfg)
n_params = sum(x.size for x in jax.tree.leaves(params))
engine, _, _, _ = deepspeed_tpu.initialize(
    model=M.make_loss_fn(cfg), model_parameters=params,
    config={{"train_batch_size": batch, "bf16": {{"enabled": True}},
            "zero_optimization": {{"stage": 1}},
            "optimizer": {{"type": "adamw", "params": {{"lr": 1e-4}}}},
            "steps_per_print": 10_000}})
tokens = np.random.default_rng(0).integers(
    0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
jax.block_until_ready(engine.train_batch({{"tokens": tokens}})["loss"])
ts = []
for _ in range(steps):
    t0 = time.perf_counter()
    float(engine.train_batch({{"tokens": tokens}})["loss"])
    ts.append(time.perf_counter() - t0)
ts.sort()
dt = ts[len(ts)//2]
print(json.dumps({{"kind": kind, "experts": {experts}, "k": {k},
    "params_M": round(n_params/1e6, 1), "batch": batch, "seq": seq,
    "step_ms": round(dt*1e3, 1),
    "tokens_per_s": round(batch*seq/dt, 1)}}))
"""


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    batch, seq = 8, 1024
    grid = [("dense", 0, 0), ("moe", 8, 1), ("moe", 8, 2), ("moe", 16, 1)]
    from tools._subproc import run_json

    for kind, experts, k in grid:
        run_json([sys.executable, "-c",
                  CODE.format(kind=kind, experts=experts, k=k, batch=batch,
                              seq=seq, steps=steps)],
                 1500, {"kind": kind, "experts": experts})


if __name__ == "__main__":
    main()
