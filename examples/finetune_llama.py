"""Fine-tune a llama-family model end to end: HF checkpoint -> TPU
training engine -> generation.

The reference's story for foreign checkpoints is inference-only
injection (ref: deepspeed/module_inject/replace_module.py); here the
SAME policy conversion feeds the training engine, because a model
dialect is just a GPTConfig — ZeRO, TP, SP, offload all compose.

  # tiny random llama on the virtual CPU mesh (smoke, ~2 min)
  python examples/finetune_llama.py

  # a real HF checkpoint directory (e.g. a llama-2-7b export) on TPU:
  python examples/finetune_llama.py --hf-path /path/to/llama --zero 3

With no --hf-path this builds a small random-weight LlamaForCausalLM
(no network access needed) — the point is the plumbing: convert, train
with ZeRO-2 + bf16 on TPU (fp32 on CPU), save a checkpoint, reload it
into the inference engine, generate.
"""

import argparse
import sys
import tempfile

sys.path.insert(0, ".")

import jax

from deepspeed_tpu.utils import honor_platform_request, on_tpu

honor_platform_request()

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.inference.policy import resolve_model
from deepspeed_tpu.models import gpt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf-path", default=None,
                    help="HF llama checkpoint dir (default: tiny random)")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--zero", type=int, default=2)
    args = ap.parse_args()

    import transformers
    if args.hf_path:
        hf_model = transformers.AutoModelForCausalLM.from_pretrained(
            args.hf_path)
    else:
        import torch
        torch.manual_seed(0)
        hf_model = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=344,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=128,
            rms_norm_eps=1e-6))

    cfg, params = resolve_model(hf_model)
    tpu = on_tpu()
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16 if tpu else jnp.float32,
                              use_flash_attention=tpu)
    n = sum(x.size for x in jax.tree.leaves(params))
    print(f"converted llama: {cfg.n_layers}L/{cfg.d_model}d "
          f"kv={cfg.kv_heads} {n/1e6:.1f}M params")

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": args.batch,
                "bf16": {"enabled": tpu},
                "zero_optimization": {"stage": args.zero},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "steps_per_print": 1000})

    r = np.random.default_rng(0)
    toks = r.integers(0, cfg.vocab_size,
                      (args.batch, min(cfg.max_seq_len, 64) + 1))
    toks = toks.astype(np.int32)
    for i in range(args.steps):
        print(f"step {i}: loss "
              f"{float(engine.train_batch({'tokens': toks})['loss']):.4f}")

    with tempfile.TemporaryDirectory() as d:
        engine.save_checkpoint(d)
        # reload the TRAINED weights from the sharded checkpoint (the
        # checkpoint= path reshards zero shards into the skeleton)
        eng = deepspeed_tpu.init_inference(
            model=(cfg, engine.module_state_dict()), checkpoint=d,
            dtype=jnp.bfloat16 if tpu else jnp.float32)
        out = eng.generate(toks[:2, :8], max_new_tokens=8, temperature=0.0)
        print(f"generated: {out.shape[1] - 8} new tokens/row "
              f"(prefill {eng.latency_ms.get('prefill', float('nan')):.0f}ms, "
              f"decode {eng.latency_ms.get('decode_per_token', float('nan')):.1f}"
              f"ms/token)")


if __name__ == "__main__":
    main()
