"""GPT-MoE training with expert parallelism — the GPT-MoE NLG workload
analog (ref: BASELINE.json config #5; reference wiring
DeepSpeedExamples Megatron-MoE via deepspeed/moe/layer.py).

Experts shard one-per-device over the data axes (GShard expert-data
parallelism); the per-layer dispatch all-to-all is emitted by XLA from
the shardings. Runs on one chip, a CPU mesh, or any slice:

  python examples/train_moe.py --steps 30
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_moe.py --experts 8
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request

honor_platform_request()

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import moe_gpt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--experts", type=int, default=0,
                    help="0 = one expert per device")
    ap.add_argument("--top_k", type=int, default=1)
    args = ap.parse_args()

    n_dev = len(jax.devices())
    experts = args.experts or max(2, n_dev)
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=8192, n_layers=4, n_heads=8, d_model=256,
        max_seq_len=args.seq, num_experts=experts, moe_k=args.top_k,
        capacity_factor=1.25, use_flash_attention=True)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    print(f"MoE GPT: {experts} experts over {n_dev} device(s), "
          f"top-{args.top_k}")

    ds_config = {
        "train_batch_size": args.batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
        "steps_per_print": 10,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config,
        partition_rules=moe_gpt.moe_gpt_partition_rules())

    r = np.random.default_rng(0)
    base = r.zipf(1.5, size=(args.batch, args.seq + 1)).clip(
        0, cfg.vocab_size - 1)
    t0 = time.perf_counter()
    for step in range(args.steps):
        noise = r.integers(0, cfg.vocab_size, base.shape)
        keep = r.random(base.shape) < 0.9
        toks = np.where(keep, base, noise).astype(np.int32)
        m = engine.train_batch({"tokens": toks})
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(m['loss']):.4f}")
    dt = time.perf_counter() - t0
    print(f"{args.steps} steps in {dt:.1f}s "
          f"({args.steps * args.batch * args.seq / dt:.0f} tokens/s)")


if __name__ == "__main__":
    main()
