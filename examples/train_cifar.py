"""CIFAR-10 ResNet training through deepspeed_tpu.initialize — the
workload analog of the reference's first example
(ref: DeepSpeedExamples/cifar driven by docs/_tutorials/cifar-10.md;
BASELINE.json config #1: ResNet CIFAR-10, ZeRO stage 1, single host).

Runs on synthetic CIFAR-shaped data by default (this environment has no
egress to download the dataset); pass ``--data path.npz`` with arrays
``images [N,32,32,3] uint8`` / ``labels [N]`` to train on real data.

Usage: python examples/train_cifar.py [--steps 100] [--batch 128]
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request

honor_platform_request()

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import resnet


def load_data(path, n=2048):
    if path:
        with np.load(path) as z:
            return (z["images"].astype(np.float32) / 127.5 - 1.0,
                    z["labels"].astype(np.int32))
    r = np.random.default_rng(0)
    # synthetic but learnable: class-dependent channel means + noise
    labels = r.integers(0, 10, n).astype(np.int32)
    means = r.standard_normal((10, 1, 1, 3)).astype(np.float32)
    images = means[labels] + 0.5 * r.standard_normal(
        (n, 32, 32, 3)).astype(np.float32)
    return images, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--data", default=None)
    args = ap.parse_args()

    cfg = resnet.ResNetConfig()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    print(f"ResNet {resnet.num_params(cfg) / 1e6:.2f}M params")

    ds_config = {
        "train_batch_size": args.batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-3, "weight_decay": 5e-4}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 50}},
        "steps_per_print": 20,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=resnet.make_loss_fn(cfg), model_parameters=params,
        config=ds_config)

    images, labels = load_data(args.data)
    n = len(labels)
    r = np.random.default_rng(1)
    t0 = time.perf_counter()
    for step in range(args.steps):
        idx = r.integers(0, n, args.batch)
        m = engine.train_batch({"images": images[idx],
                                "labels": labels[idx]})
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(m['loss']):.4f}")
    dt = time.perf_counter() - t0
    print(f"{args.steps} steps in {dt:.1f}s "
          f"({args.steps * args.batch / dt:.0f} samples/s)")

    acc = float(resnet.accuracy(
        engine.state.params,
        {"images": images[:512], "labels": labels[:512]}, cfg))
    print(f"train-set accuracy (512 samples): {acc:.3f}")


if __name__ == "__main__":
    main()
