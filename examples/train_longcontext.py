"""Long-context GPT training with ring sequence parallelism.

The reference's long-sequence story is block-sparse attention (ref:
README.md:38 "10x longer sequences"); this framework's is EXACT
attention over a sequence sharded across chips: each device holds S/n
tokens, K/V blocks rotate over the ICI ring, and the local block runs
the Pallas flash kernel — peak attention memory per chip is
O(S_loc · block), so max trainable context scales LINEARLY with chips.

  # 8-way virtual CPU mesh, 8k tokens, ring SP (smoke: a few minutes)
  python examples/train_longcontext.py --seq 8192 --sp 8

  # Ulysses all-to-all SP instead of the ring
  python examples/train_longcontext.py --seq 8192 --sp 8 --impl ulysses

  # sliding-window attention: the ring stops rotating past the band
  python examples/train_longcontext.py --seq 8192 --sp 8 --window 1024

On a real v4/v5 pod slice, drop the CPU forcing (run under the TPU
runtime) and raise --seq into the 64k-512k range with --preset
gpt2-medium and bf16.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax

from deepspeed_tpu.utils import honor_platform_request, on_tpu

honor_platform_request()

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--sp", type=int, default=8,
                    help="sequence-parallel degree (devices in the ring)")
    ap.add_argument("--impl", default="ring", choices=["ring", "ulysses"])
    ap.add_argument("--layout", default="contiguous",
                    choices=["contiguous", "zigzag"],
                    help="ring data layout; zigzag balances the causal "
                         "triangle across the ring (~2x at large rings)")
    ap.add_argument("--window", type=int, default=None,
                    help="optional sliding-window size")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    if len(jax.devices()) % args.sp or len(jax.devices()) < args.sp:
        raise SystemExit(
            f"have {len(jax.devices())} devices; sp={args.sp} needs a "
            f"multiple of it. For a virtual mesh run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={args.sp} "
            f"JAX_PLATFORMS=cpu")

    tpu = on_tpu()
    mesh = make_mesh(MeshSpec(data=len(jax.devices()) // args.sp,
                              sequence=args.sp))
    if args.layout == "zigzag" and args.impl != "ring":
        ap.error("--layout zigzag is a ring layout; use --impl ring")
    zig = args.layout == "zigzag"
    cfg = gpt.preset(args.preset, max_seq_len=args.seq,
                     dtype=jnp.bfloat16 if tpu else jnp.float32,
                     use_flash_attention=tpu,
                     sequence_parallel=True, sp_impl=args.impl,
                     sp_layout="zigzag" if zig else "contiguous",
                     attn_window=args.window, mesh=mesh,
                     loss_chunk=2048)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(x.size for x in jax.tree.leaves(params))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": args.batch,
                "bf16": {"enabled": tpu},
                "mesh": {"data_parallel_size":
                         len(jax.devices()) // args.sp,
                         "sequence_parallel_size": args.sp},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "steps_per_print": 1000},
        mesh=mesh)

    r = np.random.default_rng(0)
    tokens = r.integers(0, cfg.vocab_size,
                        (args.batch, args.seq + 1)).astype(np.int32)
    if zig:
        # zigzag layout: derive targets, then permute tokens/targets/
        # positions once on the host (the mean loss is permutation-
        # invariant)
        from deepspeed_tpu.runtime.dataloader import zigzag_batch
        batch = zigzag_batch({"tokens": tokens}, args.sp)
    else:
        batch = {"tokens": tokens}
    print(f"{args.preset}: {n_params / 1e6:.1f}M params, seq {args.seq} "
          f"over {args.sp}-way {args.impl} SP "
          f"({args.seq // args.sp} tokens/device)"
          + (", zigzag layout" if zig else "")
          + (f", window {args.window}" if args.window else ""))

    for step in range(args.steps):
        t0 = time.perf_counter()
        loss = float(engine.train_batch(batch)["loss"])
        dt = time.perf_counter() - t0
        tps = args.batch * args.seq / dt
        print(f"step {step}: loss {loss:.4f}  {dt * 1e3:.0f}ms  "
              f"{tps:,.0f} tok/s")


if __name__ == "__main__":
    main()
