"""Speculative decoding demo: a small draft accelerates a big target
with provably identical greedy output.

  # virtual CPU mesh smoke (~2 min)
  python examples/speculative_decode.py

  # on TPU, with real model scales:
  python examples/speculative_decode.py --target gpt2-large \\
      --draft gpt2-small --new-tokens 128 --gamma 5

The demo builds both models with random weights (shared vocabulary),
compares plain target generation with speculative generation, and
asserts the outputs are IDENTICAL — the speedup (reported) comes only
from verifying gamma+1 tokens per target step instead of one.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax

from deepspeed_tpu.utils import honor_platform_request, on_tpu

honor_platform_request()

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.inference.speculative import generate_speculative
from deepspeed_tpu.models import gpt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", default="gpt2-medium")
    ap.add_argument("--draft", default=None,
                    help="draft preset (default: self-draft — see "
                         "module docstring)")
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()

    tpu = on_tpu()
    dtype = jnp.bfloat16 if tpu else jnp.float32
    seq = args.prompt_len + args.new_tokens + args.gamma + 8

    def build(preset, seed):
        cfg = gpt.preset(preset, max_seq_len=seq, dtype=dtype,
                         use_flash_attention=tpu)
        return deepspeed_tpu.init_inference(
            model=(cfg, gpt.init_params(jax.random.PRNGKey(seed), cfg)),
            dtype=dtype)

    target = build(args.target, 0)
    draft = build(args.draft, 1) if args.draft else target
    toks = np.random.default_rng(0).integers(
        0, target.cfg.vocab_size, (1, args.prompt_len)).astype(np.int32)

    # warm both paths (compiles), then measure
    target.generate(toks, max_new_tokens=args.new_tokens,
                    temperature=args.temperature)
    generate_speculative(target, draft, toks,
                         max_new_tokens=args.new_tokens, gamma=args.gamma,
                         temperature=args.temperature)

    t0 = time.perf_counter()
    ref = target.generate(toks, max_new_tokens=args.new_tokens,
                          temperature=args.temperature)
    plain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    got, stats = generate_speculative(
        target, draft, toks, max_new_tokens=args.new_tokens,
        gamma=args.gamma, temperature=args.temperature, return_stats=True)
    spec_s = time.perf_counter() - t0

    same = bool((got == ref).all())
    if args.temperature == 0.0:
        assert same, "greedy speculative output MUST equal the target's"
    print(f"target={args.target} "
          f"draft={args.draft or 'self (see docstring)'} "
          f"gamma={args.gamma}")
    print(f"plain: {args.new_tokens / plain_s:.1f} tok/s | speculative: "
          f"{args.new_tokens / spec_s:.1f} tok/s "
          f"(speedup {plain_s / spec_s:.2f}x)")
    print(f"accepted/round {stats['accepted_per_round']:.2f}, "
          f"target steps {stats['target_steps']} for {stats['tokens']} "
          f"tokens; outputs identical: {same}")


if __name__ == "__main__":
    main()
