"""Inference example: HF checkpoint injection + KV-cache generation
(the init_inference analog of the reference's inference tutorials).

  python examples/generate.py            # tiny random HF GPT-2
  python examples/generate.py --hf gpt2  # a real HF checkpoint if cached
"""

import argparse
import sys

sys.path.insert(0, ".")

from deepspeed_tpu.utils import honor_platform_request

honor_platform_request()   # make JAX_PLATFORMS=cpu work despite sitecustomize

import numpy as np

import deepspeed_tpu


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf", default=None,
                    help="HF model name (needs local cache; no egress)")
    ap.add_argument("--tokens", type=int, default=16)
    args = ap.parse_args()

    import transformers
    if args.hf:
        model = transformers.GPT2LMHeadModel.from_pretrained(args.hf)
    else:
        cfg = transformers.GPT2Config(
            vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4)
        model = transformers.GPT2LMHeadModel(cfg).eval()

    engine = deepspeed_tpu.init_inference(model=model)
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    # generate_fused runs the whole decode loop as ONE compiled program
    # (no host round-trip per token); generate() is the host-driven loop
    out = engine.generate_fused(prompt, max_new_tokens=args.tokens,
                                temperature=0.8, seed=0)
    print("prompt:", prompt[0].tolist())
    print("generated:", np.asarray(out)[0].tolist())
    print("latency:", {k: round(v, 2)
                       for k, v in engine.latency_ms.items()})


if __name__ == "__main__":
    main()
