"""Minimal GPT pretraining loop (the DeepSpeedExamples analog).

Runs on one TPU chip or any JAX backend (CPU smoke: ~a minute).

  python examples/train_gpt.py --preset gpt2-small --steps 20
  python examples/train_gpt.py --deepspeed_config examples/ds_config.json
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax

from deepspeed_tpu.utils import honor_platform_request

honor_platform_request()   # make JAX_PLATFORMS=cpu work despite sitecustomize

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import gpt


def synthetic_batches(vocab, batch, seq, seed=0):
    """Stand-in corpus: a repeating Zipf-ish stream so loss decreases."""
    r = np.random.default_rng(seed)
    base = r.zipf(1.5, size=(batch, seq + 1)).clip(0, vocab - 1)
    while True:
        noise = r.integers(0, vocab, (batch, seq + 1))
        keep = r.random((batch, seq + 1)) < 0.9
        yield {"tokens": np.where(keep, base, noise).astype(np.int32)}


def main():
    ap = argparse.ArgumentParser()
    deepspeed_tpu.add_config_arguments(ap)
    ap.add_argument("--preset", default="gpt2-small")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--packed", action="store_true",
                    help="pack variable-length synthetic documents per row "
                         "(segment-ids flash attention)")
    args = ap.parse_args()

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = gpt.preset(args.preset, max_seq_len=args.seq,
                     dtype=jnp.bfloat16, use_flash_attention=on_tpu,
                     # fused chunked CE: skips the [B,S,V] logits tensor
                     loss_chunk=2048)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    ds_config = args.deepspeed_config or {
        "train_batch_size": args.batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "optimizer": {"type": "adamw",
                      "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
        "steps_per_print": 10,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config, partition_rules=gpt.gpt_partition_rules())

    if args.packed:
        # variable-length documents packed into fixed rows — attention is
        # block-diagonal per doc, positions restart, boundaries masked
        from deepspeed_tpu.runtime.dataloader import pack_documents
        r = np.random.default_rng(0)

        def packed_batches():
            while True:
                docs = []
                out = {"tokens": np.zeros((0, 0))}
                while out["tokens"].shape[0] < args.batch:
                    docs += [r.integers(0, cfg.vocab_size,
                                        int(n)).astype(np.int32)
                             for n in r.integers(16, args.seq, args.batch)]
                    out = pack_documents(docs, args.seq + 1)
                yield {k: v[:args.batch] for k, v in out.items()}

        data = packed_batches()
    else:
        data = synthetic_batches(cfg.vocab_size, args.batch, args.seq)
    t0 = time.perf_counter()
    real_tokens = 0
    for step in range(args.steps):
        batch = next(data)
        # packed rows carry padding — count only loss-contributing tokens
        real_tokens += int(batch["loss_mask"].sum()) \
            if "loss_mask" in batch else args.batch * args.seq
        m = engine.train_batch(batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(m['loss']):.4f} "
                  f"lr {float(m['lr']):.2e}")
    dt = time.perf_counter() - t0
    print(json.dumps({"steps": args.steps,
                      "tokens_per_sec": round(real_tokens / dt, 1)}))


if __name__ == "__main__":
    main()
