"""Interleaved 1F1B (virtual pipeline stages): schedule tables + executor.

The schedule layer compiles a megatron-style interleaved instruction
stream into static lockstep tick tables (schedule.py
interleaved_1f1b_tables); the executor (engine.py _interleaved_program)
replays them inside one lax.scan. Tests mirror the reference's
device-free schedule validation (ref: tests/unit/test_pipe_schedule.py)
plus dense-parity of the executor on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import gpt
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
from deepspeed_tpu.runtime.pipe.schedule import (
    _interleaved_rank_order, interleaved_1f1b_tables)


# ---------------------------------------------------------------------------
# schedule tables (no devices)
# ---------------------------------------------------------------------------

def test_v1_reduces_to_classic_1f1b_tick_count():
    for P, M in [(2, 4), (4, 8), (8, 16)]:
        tab = interleaved_1f1b_tables(P, 1, M)
        assert tab["fwd_c"].shape[1] == M + 2 * P - 2


@pytest.mark.parametrize("P,v,M", [(2, 2, 4), (4, 2, 8), (4, 3, 12),
                                   (8, 4, 8)])
def test_schedule_completeness(P, v, M):
    """Every (chunk, microbatch) F and B appears exactly once per device."""
    tab = interleaved_1f1b_tables(P, v, M)
    T = tab["fwd_c"].shape[1]
    for d in range(P):
        for kind in ("fwd", "bwd"):
            seen = set()
            for t in range(T):
                if tab[f"{kind}_valid"][d, t]:
                    key = (int(tab[f"{kind}_c"][d, t]),
                           int(tab[f"{kind}_m"][d, t]))
                    assert key not in seen, (kind, d, key)
                    seen.add(key)
            assert seen == {(c, m) for c in range(v) for m in range(M)}


@pytest.mark.parametrize("P,v,M", [(2, 2, 4), (4, 2, 8), (8, 4, 8)])
def test_schedule_dependencies(P, v, M):
    """Independent re-check: F needs the previous virtual stage's F at an
    earlier tick; B needs the next virtual stage's B at an earlier tick
    and the local F no later than itself (same tick only for the head)."""
    tab = interleaved_1f1b_tables(P, v, M)
    T = tab["fwd_c"].shape[1]
    V = v * P
    f_tick, b_tick = {}, {}
    for d in range(P):
        for t in range(T):
            if tab["fwd_valid"][d, t]:
                f_tick[(int(tab["fwd_c"][d, t]) * P + d,
                        int(tab["fwd_m"][d, t]))] = t
            if tab["bwd_valid"][d, t]:
                b_tick[(int(tab["bwd_c"][d, t]) * P + d,
                        int(tab["bwd_m"][d, t]))] = t
    for (vs, m), t in f_tick.items():
        if vs > 0:
            assert f_tick[(vs - 1, m)] < t, ("F dep", vs, m)
    for (vs, m), t in b_tick.items():
        if vs == V - 1:
            assert f_tick[(vs, m)] <= t, ("head F->B", vs, m)
        else:
            assert b_tick[(vs + 1, m)] < t, ("B dep", vs, m)
            assert f_tick[(vs, m)] <= t, ("recompute input", vs, m)


def test_interleaving_cuts_wall_time():
    """In chunk-work units (tick cost ~ 1/v), deeper interleaving beats
    the classic schedule where bubble dominates (small M/P)."""
    P, M = 8, 8
    classic = M + 2 * P - 2
    for v in (2, 4):
        T = interleaved_1f1b_tables(P, v, M)["fwd_c"].shape[1]
        assert T / v < classic, (v, T)
    # and v=4 beats v=2
    t2 = interleaved_1f1b_tables(P, 2, M)["fwd_c"].shape[1] / 2
    t4 = interleaved_1f1b_tables(P, 4, M)["fwd_c"].shape[1] / 4
    assert t4 < t2


def test_rank_order_warmup_structure():
    """Device P-1 has the fewest warmup forwards; order alternates F/B
    after warmup (megatron 1F1B shape)."""
    P, v, M = 4, 2, 8
    for d in range(P):
        ops = _interleaved_rank_order(P, v, M, d)
        kinds = [o[0] for o in ops]
        warmup = min((P - d - 1) * 2 + (v - 1) * P, M * v)
        assert kinds[:warmup] == ["F"] * warmup
        steady = kinds[warmup:warmup + 2 * (M * v - warmup)]
        assert steady == ["F", "B"] * (M * v - warmup)
    with pytest.raises(ValueError, match="divisible"):
        interleaved_1f1b_tables(4, 2, 6)      # M % P != 0


# ---------------------------------------------------------------------------
# executor parity (8-device CPU mesh)
# ---------------------------------------------------------------------------

def _tiny_cfg(n_layers):
    return gpt.GPTConfig(vocab_size=128, n_layers=n_layers, n_heads=4,
                         d_model=32, max_seq_len=16, dropout=0.0,
                         dtype=jnp.float32, remat=False,
                         use_flash_attention=False)


def test_interleaved_loss_matches_dense(devices):
    cfg = _tiny_cfg(n_layers=8)          # 4 stages x 2 chunks x 1 layer... 8
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 17))
    batch = {"tokens": jnp.asarray(tokens.astype(np.int32))}
    ref = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0),
                            cfg, deterministic=True))
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                        num_micro=4,
                                        schedule="interleaved",
                                        virtual_chunks=2)
    with jax.set_mesh(mesh):
        got = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_interleaved_buffer_wraparound_parity(devices):
    """num_micro > buffer depth: the act/cot ring-buffer modulo actually
    wraps (k_act < M) — the trickiest slot arithmetic in the executor."""
    cfg = _tiny_cfg(n_layers=8)          # 2 stages x 4 chunks x 1 layer
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(4).integers(0, 128, (16, 17))
    batch = {"tokens": jnp.asarray(tokens.astype(np.int32))}
    ref = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0),
                            cfg, deterministic=True))
    mesh = make_mesh(MeshSpec(pipe=2, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2,
                                        num_micro=8,
                                        schedule="interleaved",
                                        virtual_chunks=4)
    with jax.set_mesh(mesh):
        got = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_interleaved_grads_match_dense(devices):
    cfg = _tiny_cfg(n_layers=4)          # 2 stages x 2 chunks x 1 layer
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(1).integers(0, 128, (4, 17))
    batch = {"tokens": jnp.asarray(tokens.astype(np.int32))}
    g_ref = jax.grad(lambda p: gpt.loss_fn(p, dict(batch),
                                           jax.random.PRNGKey(0), cfg,
                                           deterministic=True))(params)
    mesh = make_mesh(MeshSpec(pipe=2, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2,
                                        num_micro=2,
                                        schedule="interleaved",
                                        virtual_chunks=2)
    with jax.set_mesh(mesh):
        g = jax.jit(jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(0))))(params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_ref),
            jax.tree_util.tree_leaves_with_path(g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_interleaved_engine_trains(devices):
    import deepspeed_tpu
    cfg = _tiny_cfg(n_layers=8)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                        num_micro=4,
                                        schedule="interleaved",
                                        virtual_chunks=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_batch_size": 8,
                "mesh": {"pipeline_parallel_size": 4,
                         "data_parallel_size": 2},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
                "steps_per_print": 1000},
        mesh=mesh)
    tokens = np.random.default_rng(2).integers(0, 128, (8, 17))
    batch = {"tokens": tokens.astype(np.int32)}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.2, losses


def test_interleaved_rejects_bad_config(devices):
    from deepspeed_tpu.runtime.pipe.engine import make_pipelined_loss_fn
    with pytest.raises(ValueError, match="virtual_chunks"):
        make_pipelined_loss_fn(None, None, None, 4, 2, 4, None, None,
                               schedule="interleaved", virtual_chunks=1)


try:
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=25, deadline=None)
    @given(P=st.integers(2, 8), v=st.integers(1, 4),
           groups=st.integers(1, 3))
    def test_schedule_properties_random(P, v, groups):
        """Hypothesis sweep of the generator invariants: completeness,
        dependency order, and the v=1 classic tick count, for random
        (stages, chunks, microbatch-group) shapes."""
        M = P * groups
        tab = interleaved_1f1b_tables(P, v, M)
        T = tab["fwd_c"].shape[1]
        V = v * P
        f_tick, b_tick = {}, {}
        for d in range(P):
            seen_f, seen_b = set(), set()
            for t in range(T):
                if tab["fwd_valid"][d, t]:
                    key = (int(tab["fwd_c"][d, t]), int(tab["fwd_m"][d, t]))
                    assert key not in seen_f
                    seen_f.add(key)
                    f_tick[(key[0] * P + d, key[1])] = t
                if tab["bwd_valid"][d, t]:
                    key = (int(tab["bwd_c"][d, t]), int(tab["bwd_m"][d, t]))
                    assert key not in seen_b
                    seen_b.add(key)
                    b_tick[(key[0] * P + d, key[1])] = t
            full = {(c, m) for c in range(v) for m in range(M)}
            assert seen_f == full and seen_b == full
        for (vs, m), t in f_tick.items():
            if vs > 0:
                assert f_tick[(vs - 1, m)] < t
        for (vs, m), t in b_tick.items():
            if vs == V - 1:
                assert f_tick[(vs, m)] <= t
            else:
                assert b_tick[(vs + 1, m)] < t
                assert f_tick[(vs, m)] <= t
        if v == 1:
            assert T == M + 2 * P - 2
except ImportError:            # pragma: no cover - hypothesis is baked in
    pass
