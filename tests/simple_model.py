"""Tiny real-model fixtures (ref: tests/unit/simple_model.py:11 SimpleModel,
:40 SimpleMoEModel). Pure-jax: params pytree + loss function."""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def simple_model_params(hidden_dim: int = 16, nlayers: int = 2,
                        seed: int = 0) -> Dict:
    """An MLP regression model: nlayers linear layers + head."""
    rng = np.random.default_rng(seed)
    params = {}
    for i in range(nlayers):
        params[f"layer_{i}"] = {
            "kernel": jnp.asarray(
                rng.standard_normal((hidden_dim, hidden_dim)) / np.sqrt(hidden_dim),
                jnp.float32),
            "bias": jnp.zeros((hidden_dim,), jnp.float32),
        }
    params["head"] = {
        "kernel": jnp.asarray(
            rng.standard_normal((hidden_dim, 1)) / np.sqrt(hidden_dim), jnp.float32),
        "bias": jnp.zeros((1,), jnp.float32),
    }
    return params


def simple_model_loss(params: Dict, batch: Tuple, rng=None) -> jnp.ndarray:
    """MSE loss. batch = (x [B, H], y [B])."""
    x, y = batch["x"], batch["y"]
    h = x
    i = 0
    while f"layer_{i}" in params:
        p = params[f"layer_{i}"]
        h = jnp.tanh(h @ p["kernel"] + p["bias"])
        i += 1
    pred = (h @ params["head"]["kernel"] + params["head"]["bias"]).squeeze(-1)
    return jnp.mean(jnp.square(pred - y))


def random_batch(batch_size: int, hidden_dim: int = 16, seed: int = 0) -> Dict:
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((batch_size, hidden_dim)).astype(np.float32)
    w = rng.standard_normal((hidden_dim,)).astype(np.float32)
    y = np.tanh(x @ w)
    return {"x": x, "y": y}
