"""Curriculum learning, progressive layer drop, TiledLinear, sparse
tensors (ref: tests/unit/test_curriculum_learning.py style loss checks,
tests/unit/test_pld.py theta schedule checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler
from deepspeed_tpu.runtime.progressive_layer_drop import (
    ProgressiveLayerDrop, theta_schedule)
from deepspeed_tpu.runtime.sparse_tensor import (
    SparseTensor, average_sparse, sparse_all_reduce)
from deepspeed_tpu.runtime.zero import tiling
from tests.simple_model import random_batch, simple_model_loss, simple_model_params


# ----------------------------------------------------------- curriculum

def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    d = [s.update_difficulty(t) for t in range(1, 120, 10)]
    assert d[0] == 8 and d[-1] == 64
    assert all(x % 8 == 0 for x in d)
    assert d == sorted(d)  # monotone


def test_fixed_root_schedule():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_root",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8, "root_degree": 2}})
    # sqrt schedule reaches a given difficulty earlier than linear
    assert s.get_difficulty(25) >= 8 + (64 - 8) // 2 - 8
    assert s.update_difficulty(200) == 64


def test_fixed_discrete_schedule():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 2,
        "max_difficulty": 6, "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [2, 4, 6], "max_step": [5, 10]}})
    assert s.update_difficulty(3) == 2
    assert s.update_difficulty(7) == 4
    assert s.update_difficulty(100) == 6


def test_curriculum_state_roundtrip():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    s.update_difficulty(50)
    state = s.get_state()
    s2 = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    s2.set_state(state)
    assert s2.get_current_difficulty() == s.get_current_difficulty()


def test_engine_curriculum_truncates_seq(devices):
    """GPT under seqlen curriculum: short sequences early, full later
    (ref: engine hook runtime/engine.py:1548)."""
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=32, dropout=0.0)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    seen_lens = []
    base_loss = gpt.make_loss_fn(cfg)

    def spy_loss(p, batch, rng):
        seen_lens.append(batch["tokens"].shape[1])
        return base_loss(p, batch, rng)

    ds_cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 8, "max_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 6,
                                "difficulty_step": 8}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=spy_loss, model_parameters=params, config=ds_cfg)
    toks = np.random.default_rng(0).integers(0, 64, (8, 32)).astype(np.int32)
    for _ in range(8):
        engine.train_batch({"tokens": toks})
    # spy records the post-truncation seqlen (minus the shift in loss_fn)
    assert min(seen_lens) < max(seen_lens)
    assert max(seen_lens) == 32
    assert engine.curriculum_scheduler.get_current_difficulty() == 32


# ------------------------------------------------------------------ pld

def test_pld_theta_decays():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    vals = []
    for t in [0, 10, 100, 1000]:
        pld.update_state(t)
        vals.append(pld.get_theta())
    assert vals[0] == 1.0
    assert vals == sorted(vals, reverse=True)
    assert abs(vals[-1] - 0.5) < 1e-3  # asymptote at theta


def test_pld_theta_schedule_traceable():
    out = jax.jit(lambda s: theta_schedule(s, 0.5, 0.01))(jnp.int32(100))  # dslint: disable=DS002 — one-shot traceability probe, cache churn is the point under test
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    pld.update_state(100)
    assert abs(float(out) - pld.get_theta()) < 1e-5


def test_gpt_forward_with_pld(devices):
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=4, n_heads=2, d_model=32,
                        max_seq_len=16, dropout=0.0)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    full = gpt.forward(params, toks, cfg, jax.random.PRNGKey(1),
                       deterministic=False, pld_theta=jnp.float32(1.0))
    ref = gpt.forward(params, toks, cfg, jax.random.PRNGKey(1),
                      deterministic=False)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ref), atol=1e-5)
    # theta=0: layers drop (keep prob 1 - l/L), output differs for some seed
    dropped = gpt.forward(params, toks, cfg, jax.random.PRNGKey(1),
                          deterministic=False, pld_theta=jnp.float32(0.0))
    assert float(jnp.max(jnp.abs(dropped - ref))) > 1e-6


def test_engine_pld_training(devices):
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=16, dropout=0.0)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.01},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds_cfg)
    r = np.random.default_rng(0)
    losses = []
    for i in range(12):
        toks = r.integers(0, 64, (8, 16)).astype(np.int32)
        losses.append(float(engine.train_batch({"tokens": toks})["loss"]))
    assert engine.progressive_layer_drop.get_theta() < 1.0
    assert losses[-1] < losses[0]


# ----------------------------------------------------------- tiled linear

def test_tiled_linear_matches_dense(rng):
    x = jnp.asarray(rng.standard_normal((4, 6, 32)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((32, 24)) * 0.1, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((24,)) * 0.1, jnp.float32)
    for in_s, out_s in [(1, 1), (4, 1), (1, 3), (4, 3)]:
        params = tiling.from_dense(kernel, bias, in_s, out_s)
        y = tiling.tiled_linear(x, params)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x @ kernel + bias),
                                   rtol=1e-4, atol=1e-4)


def test_tiled_linear_grad_matches_dense(rng):
    x = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((32, 16)) * 0.1, jnp.float32)
    params = tiling.from_dense(kernel, None, 4, 2)

    g_tiled = jax.grad(lambda p: jnp.sum(tiling.tiled_linear(x, p) ** 2))(params)
    dense_k, _ = tiling.to_dense({"kernel": g_tiled["kernel"]})
    g_dense = jax.grad(lambda k: jnp.sum((x @ k) ** 2))(kernel)
    np.testing.assert_allclose(np.asarray(dense_k), np.asarray(g_dense),
                               rtol=1e-3, atol=1e-3)


def test_tiled_linear_roundtrip_and_validation(rng):
    k = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)
    p = tiling.from_dense(k, None, 2, 3)
    k2, _ = tiling.to_dense(p)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k))
    with pytest.raises(RuntimeError):
        tiling.tiled_linear_init(jax.random.PRNGKey(0), 10, 10, in_splits=3)
    with pytest.raises(RuntimeError):
        tiling.tiled_linear_init(jax.random.PRNGKey(0), 10, 10, in_splits=11)
    p3 = tiling.tiled_linear_init(jax.random.PRNGKey(0), 16, 8,
                                  in_splits=4, out_splits=2)
    assert p3["kernel"].shape == (2, 4, 4, 4)
    out = tiling.tiled_linear(jnp.ones((2, 16)), p3, combine_out_splits=False)
    assert len(out) == 2 and out[0].shape == (2, 4)


# --------------------------------------------------------- sparse tensor

def test_sparse_tensor_roundtrip(rng):
    dense = jnp.zeros((16, 4), jnp.float32)
    dense = dense.at[jnp.asarray([1, 5, 9])].set(
        jnp.asarray(rng.standard_normal((3, 4)), jnp.float32))
    st = SparseTensor.from_dense(dense, max_rows=4)
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense),
                               atol=1e-6)
    compressed, full = st.sparse_size()
    assert full == 64 and compressed < full


def test_sparse_tensor_add():
    a = SparseTensor(jnp.asarray([0]), jnp.ones((1, 4)), (8, 4))
    b = SparseTensor(jnp.asarray([0]), jnp.ones((1, 4)), (8, 4))
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()[0]), 2.0)
    [avg] = average_sparse([a], world_size=2)
    np.testing.assert_allclose(np.asarray(avg.to_dense()[0]), 1.0)


def test_sparse_all_reduce_shard_map(devices):
    """Sparse allreduce under shard_map over 8 devices matches the dense
    psum (ref: engine.py:2211-2236 sparse_allreduce via allgather)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("data",))
    rows, cols, cap = 32, 4, 4
    r = np.random.default_rng(0)
    # per-device sparse contributions
    idx = jnp.asarray(r.integers(0, rows, (8, cap)), jnp.int32)
    val = jnp.asarray(r.standard_normal((8, cap, cols)), jnp.float32)

    def body(i, v):
        return sparse_all_reduce(i[0], v[0], (rows, cols), "data")

    out = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P(),
        # the scatter-add of all-gathered pairs is replicated by
        # construction; the varying-manual-axes checker can't see that
        check_vma=False))(idx, val)

    expect = np.zeros((rows, cols), np.float32)
    for d in range(8):
        for j in range(cap):
            expect[int(idx[d, j])] += np.asarray(val[d, j])
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-5)


def test_engine_pld_with_offload(devices):
    """PLD composes with host-offloaded Adam: theta rides the grad-only
    program as a traced function of the applied-step counter (the
    exclusion VERDICT r2 flagged; ref engine.py:1542 + cpu_offload
    compose in the reference)."""
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=16, dropout=0.0)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_cfg = {
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "cpu"}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.01},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds_cfg)
    assert engine.offload_enabled
    r = np.random.default_rng(0)
    losses = []
    for i in range(12):
        toks = r.integers(0, 64, (8, 16)).astype(np.int32)
        losses.append(float(engine.train_batch({"tokens": toks})["loss"]))
    assert engine.progressive_layer_drop.get_theta() < 1.0
    assert losses[-1] < losses[0]
