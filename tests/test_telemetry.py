"""Serving telemetry tests (tentpole: deepspeed_tpu/telemetry/ wired
through inference/serving.py; docs/OBSERVABILITY.md).

Layers:
  1. registry unit tests — histogram bucket math vs a numpy reference,
     Prometheus exposition golden text, Monitor accepting histogram
     summaries;
  2. tracer unit tests — ring-buffer wrap accounting, Chrome-trace
     span building;
  3. serving integration — span ordering across evict/requeue, the
     read-only stats view, the deadline clock decoupled from the steps
     metric, no-op mode recording nothing (and costing nothing);
  4. chaos — a seeded fault run whose injected events land in the
     trace at their exact visit indices, and the acceptance gate:
     telemetry ON is token-bit-identical to OFF with ZERO steady-state
     recompiles, while the Perfetto + Prometheus exports reconstruct
     every request lifecycle.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.telemetry import (Histogram, MetricsRegistry,
                                     NoopTelemetry, RequestTracer,
                                     StepBreakdown, Telemetry,
                                     merge_registries, resolve_telemetry)
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault
from deepspeed_tpu.utils.monitor import Monitor
from tools.trace_analyze import analyze_serving_trace


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


@pytest.fixture(scope="module")
def eng(devices):
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


# ---------------------------------------------------------------------------
# registry unit tests (pure host — no devices needed)
# ---------------------------------------------------------------------------

def test_histogram_bucket_math_vs_numpy():
    """Cumulative bucket counts are exact against ``data <= le`` and the
    interpolated percentiles track numpy within one bucket width."""
    rng = np.random.default_rng(0)
    data = rng.uniform(0.0, 10.0, 2000)
    uppers = np.linspace(0.1, 10.0, 100)          # width 0.1
    h = Histogram("lat", buckets=uppers)
    for v in data:
        h.observe(v)
    cum = 0
    for i, ub in enumerate(h.uppers):
        cum += h.counts[i]
        assert cum == int((data <= ub).sum())
    assert h.count == 2000
    assert abs(h.sum - data.sum()) < 1e-6
    for q in (10, 50, 90, 95, 99):
        assert abs(h.percentile(q) - np.percentile(data, q)) <= 0.15, q
    # overflow bucket clamps to the max observed value
    h2 = Histogram("o", buckets=(1.0,))
    h2.observe(5.0)
    h2.observe(7.0)
    assert h2.counts[-1] == 2 and h2.percentile(99) == 7.0
    assert Histogram("e", buckets=(1.0,)).percentile(50) == 0.0


def test_histogram_window_summary_vs_numpy():
    """The windowed view (observability tentpole): ``window_summary``
    over the recent-observation ring is EXACT against numpy's linear
    percentile on the same sample — no bucket quantization — and the
    time filter keeps only observations inside ``[now - window, now]``."""
    rng = np.random.default_rng(7)
    data = rng.uniform(0.0, 20.0, 500)
    h = Histogram("lat")
    for i, v in enumerate(data):
        h.observe(v, at=float(i))
    # whole-ring summary (window=None) == numpy on the raw sample
    s = h.window_summary()
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        assert s[key] == pytest.approx(np.percentile(data, q), abs=1e-12)
    assert s["mean"] == pytest.approx(data.mean())
    assert s["count"] == 500
    # time-filtered: only the last 100 clock units (at >= 399)
    tail = data[399:]
    sw = h.window_summary(window=100.0, now=499.0)
    assert sw["count"] == len(tail)
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        assert sw[key] == pytest.approx(np.percentile(tail, q), abs=1e-12)
    # ``now`` defaults to the newest observation's clock
    assert h.window_summary(window=100.0) == sw
    # an empty window is all-zeros, not an error
    assert h.window_summary(window=1.0, now=1e9)["count"] == 0
    # cumulative view is untouched by the ring
    assert h.count == 500 and abs(h.sum - data.sum()) < 1e-6


def test_histogram_window_ring_bounded():
    """The ring is memory-bounded: only the most recent
    ``window_capacity`` observations survive; without explicit ``at``
    the observation sequence number is the clock."""
    h = Histogram("b", window_capacity=16)
    for i in range(100):
        h.observe(float(i))
    vals = h.window_values()
    assert vals == [float(i) for i in range(84, 100)]
    assert h.count == 100                       # cumulative still exact
    # sequence clock: a window of 4 keeps the last 5 observations
    # (at >= now - window, inclusive)
    assert h.window_values(window=4) == [95.0, 96.0, 97.0, 98.0, 99.0]


def test_merge_registries_fleet_fold():
    """``merge_registries`` is the fleet aggregation: counters and
    gauges sum, histograms with identical ladders merge bucket-wise and
    interleave their rings by clock; mismatched ladders refuse."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("serving_completed", "done").inc(3)
    b.counter("serving_completed").inc(4)
    b.counter("only_b").inc(1)
    a.gauge("queue_depth").set(2)
    b.gauge("queue_depth").set(5)
    ha = a.histogram("serving_ttft", buckets=(1.0, 4.0))
    hb = b.histogram("serving_ttft", buckets=(1.0, 4.0))
    ha.observe(0.5, at=1.0)
    ha.observe(6.0, at=3.0)
    hb.observe(2.0, at=2.0)
    m = merge_registries([a, b])
    assert m.counter("serving_completed").value == 7
    assert m.counter("only_b").value == 1
    assert m.gauge("queue_depth").value == 7
    hm = m.histogram("serving_ttft")
    assert hm.count == 3 and hm.sum == pytest.approx(8.5)
    assert list(hm.counts) == [1, 1, 1]          # (<=1, <=4, +Inf)
    assert hm.window_values() == [0.5, 2.0, 6.0]   # clock-ordered
    # exposition of the merged registry is ordinary cumulative text
    assert 'serving_ttft_bucket{le="+Inf"} 3' in m.to_prometheus()
    # ladder mismatch is a hard error, not silent garbage
    c = MetricsRegistry()
    c.histogram("serving_ttft", buckets=(2.0,)).observe(1.0)
    with pytest.raises(ValueError):
        merge_registries([a, c])


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "requests seen")
    c.inc()
    c.inc(2)
    reg.gauge("queue_depth").set(3)
    h = reg.histogram("latency_s", "request latency", buckets=(0.25, 1.0))
    for v in (0.125, 0.5, 4.0):
        h.observe(v)
    assert reg.to_prometheus() == (
        "# HELP requests_total requests seen\n"
        "# TYPE requests_total counter\n"
        "requests_total 3\n"
        "# TYPE queue_depth gauge\n"
        "queue_depth 3\n"
        "# HELP latency_s request latency\n"
        "# TYPE latency_s histogram\n"
        'latency_s_bucket{le="0.25"} 1\n'
        'latency_s_bucket{le="1"} 2\n'
        'latency_s_bucket{le="+Inf"} 3\n'
        "latency_s_sum 4.625\n"
        "latency_s_count 3\n")
    # get-or-create returns the same instance; snapshot is plain data
    assert reg.counter("requests_total") is c
    snap = reg.snapshot()
    assert snap["counters"]["requests_total"] == 3
    assert snap["histograms"]["latency_s"]["count"] == 3.0


def test_monitor_accepts_histogram_summaries(tmp_path, monkeypatch):
    """Registry scalars — including histogram summary mappings — flow
    through Monitor.write_scalars as tag/p50-style sub-scalars."""
    from deepspeed_tpu.utils import monitor as monitor_mod
    # skip the tensorboard backend probe (a multi-second torch import);
    # this test targets the csv/jsonl mirror
    monkeypatch.setattr(monitor_mod, "_try_tensorboard_writer",
                        lambda log_dir: None)
    reg = MetricsRegistry()
    reg.counter("serving_completed").inc(4)
    h = reg.histogram("serving_ttft", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0):
        h.observe(v)
    mon = Monitor(output_path=str(tmp_path), job_name="tele")
    mon.write_scalars(reg.to_scalars(step=7))
    mon.close()
    rows = [json.loads(l) for l in
            open(tmp_path / "tele" / "scalars.jsonl")]
    tags = {r["tag"]: r["value"] for r in rows}
    assert tags["serving_completed"] == 4.0
    assert {"serving_ttft/p50", "serving_ttft/p95", "serving_ttft/p99",
            "serving_ttft/mean", "serving_ttft/count"} <= set(tags)
    assert tags["serving_ttft/count"] == 3.0
    assert all(r["step"] == 7 for r in rows)


# ---------------------------------------------------------------------------
# tracer unit tests
# ---------------------------------------------------------------------------

def test_tracer_ring_buffer_wrap():
    tr = RequestTracer(capacity=8)
    for i in range(20):
        tr.event("tick", rid="r", step=i)
    recs = tr.records()
    assert len(recs) == 8 and tr.dropped == 12
    assert [r[3] for r in recs] == list(range(12, 20))   # oldest first
    assert tr.to_chrome_trace()["dropped_events"] == 12
    tr.reset()
    assert tr.records() == [] and tr.dropped == 0


def test_tracer_builds_ordered_spans():
    """A synthetic evict/requeue lifecycle renders as repeated
    queued/prefill/decode spans in timestamp order."""
    clock = iter(float(i) for i in range(100))
    tr = RequestTracer(capacity=64, clock=lambda: next(clock))
    tr.event("enqueue", rid="a", step=0)
    tr.event("admit", rid="a", step=1, slot=0, matched=4)
    tr.event("prefill_done", rid="a", step=2, slot=0)
    tr.event("evict", rid="a", step=3, slot=0)
    tr.event("admit", rid="a", step=4, slot=1, matched=0)
    tr.event("prefill_done", rid="a", step=5, slot=1)
    tr.event("finish", rid="a", step=6, slot=1, state="done", generated=3)
    spans = [(e["ts"], e["name"], e["args"]) for e in
             tr.to_chrome_trace()["traceEvents"]
             if e.get("ph") == "X" and e.get("cat") == "request"]
    spans.sort()
    assert [s[1] for s in spans] == ["queued", "prefill", "decode",
                                    "queued", "prefill", "decode"]
    assert spans[1][2]["prefix_hit"] is True
    assert spans[4][2]["prefix_hit"] is False
    assert spans[2][2]["evicted"] is True
    assert spans[5][2]["state"] == "done"


def test_step_breakdown_sampling():
    reg = MetricsRegistry()
    tr = RequestTracer(capacity=16)
    synced = []
    bd = StepBreakdown(reg, tr, sample_every=3)
    assert bd.begin(0, sync=lambda: synced.append(1)) is True
    bd.lap("admission")
    bd.lap("prefill")
    bd.lap("decode")
    bd.finish(occupancy=2)
    assert bd.begin(1) is False          # not a sampled step
    bd.lap("admission")
    bd.finish()
    assert len(synced) == 5              # begin + 3 laps + bookkeeping
    assert reg.histogram("serving_step_s").count == 1
    assert reg.histogram("serving_step_decode_s").count == 1
    phases = [r for r in tr.records() if r[1] == "step_phase"]
    assert len(phases) == 1 and phases[0][5]["occupancy"] == 2


def test_resolve_telemetry_env_and_flag(monkeypatch):
    monkeypatch.delenv("DS_TELEMETRY", raising=False)
    assert resolve_telemetry(None) is False      # default off
    monkeypatch.setenv("DS_TELEMETRY", "on")
    assert resolve_telemetry(None) is True
    monkeypatch.setenv("DS_TELEMETRY", "off")
    assert resolve_telemetry(None) is False
    assert resolve_telemetry(True) is True       # explicit flag wins
    monkeypatch.setenv("DS_TELEMETRY", "on")
    assert resolve_telemetry(False) is False


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

def test_serving_span_ordering_across_evict_requeue(eng):
    """The tight-pool eviction workload: the preempted request's
    timeline shows enqueue -> admit -> evict -> re-admit -> finish in
    order, and the Chrome-trace export renders it as repeated
    queued/prefill(/decode) spans ending in state=done."""
    p1, p2 = prompts_of((10, 9), seed=9)
    tel = Telemetry(sample_every=4)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                        prefill_chunk=8, telemetry=tel)
    srv.cache.watermark = 0
    srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
             ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
    assert srv.stats["evictions"] >= 1
    victim = next(r.rid for r in srv.finished if r.evictions > 0)
    seq = [r[1] for r in tel.tracer.events_of(victim)]
    assert seq[0] == "enqueue" and seq[-1] == "finish"
    assert seq.count("admit") == 1 + seq.count("evict")   # re-admitted
    assert 0 < seq.index("admit") < seq.index("evict") \
        < len(seq) - 1 - seq[::-1].index("admit")
    trace = tel.tracer.to_chrome_trace()
    spans = sorted((e["ts"], e["name"], e["args"]) for e in
                   trace["traceEvents"]
                   if e.get("ph") == "X" and e.get("cat") == "request"
                   and e["args"]["rid"] == victim)
    names = [s[1] for s in spans]
    assert names[0] == "queued" and names.count("queued") >= 2
    assert spans[-1][2].get("state") == "done"
    # every request's terminal span carries a terminal state
    for r in srv.finished:
        rid_spans = sorted((e["ts"], e["args"].get("state")) for e in
                           trace["traceEvents"]
                           if e.get("ph") == "X"
                           and e.get("cat") == "request"
                           and e["args"]["rid"] == str(r.rid))
        assert rid_spans[-1][1] == r.state


def test_stats_view_read_only_and_registry_backed(eng):
    p, = prompts_of((6,), seed=3)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24)
    srv.run([ServeRequest(rid="x", prompt=p, max_new_tokens=4)])
    # same keys and values as the old dict contract
    assert srv.stats["completed"] == 1 and srv.stats["admitted"] == 1
    assert set(dict(srv.stats)) == {
        "steps", "occupancy_sum", "peak_occupancy", "evictions",
        "admitted", "completed", "prefill_chunks", "decode_steps",
        "timeouts", "shed", "retries", "evict_capped", "watchdog_trips",
        "backpressure", "prefix_hits", "prefix_tokens_saved",
        "spec_steps", "spec_slot_steps", "spec_proposed",
        "spec_accepted", "spec_emitted", "spec_fallbacks",
        "sampled_tokens", "stop_hits", "spec_k_capped",
        "horizon_fallbacks"}
    with pytest.raises(TypeError):
        srv.stats["steps"] = 99          # read-only view
    # the registry is the writable surface
    assert srv.metrics.counter("serving_completed").value == 1
    assert srv.stats["completed"] == srv.metrics.snapshot()[
        "counters"]["serving_completed"]


def test_deadline_clock_decoupled_from_steps_metric(eng):
    """The satellite fix: ``stats["steps"]`` used to BE the deadline
    clock, so bumping the metric skewed every relative deadline. Now the
    clock is private — a skewed counter changes reporting only."""
    p1, p2 = prompts_of((6, 7), seed=5)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24)
    srv.metrics.counter("serving_steps").inc(1000)   # hostile skew
    out = srv.run([ServeRequest(rid="d", prompt=p1, max_new_tokens=6,
                                deadline=50.0),
                   ServeRequest(rid="ok", prompt=p2, max_new_tokens=6)])
    done = {r.rid: r for r in srv.finished}
    # under the old clock now=1000 >= 50 would time "d" out instantly
    assert done["d"].state == "done" and len(done["d"].out) == 6
    assert done["ok"].state == "done"
    assert sorted(out) == ["d", "ok"]


def test_noop_mode_records_nothing_and_costs_nothing(eng):
    p, = prompts_of((8,), seed=2)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        telemetry=False)
    srv.run([ServeRequest(rid="n", prompt=p, max_new_tokens=6)])
    assert isinstance(srv.telemetry, NoopTelemetry)
    assert not srv.telemetry.enabled
    assert srv.telemetry.tracer.records() == []
    # no latency histograms materialize off-mode (stats counters only)
    assert "serving_ttft" not in srv.metrics.names()
    # stats stay fully live
    assert srv.stats["completed"] == 1 and srv.stats["steps"] > 0
    # overhead guard: the no-op record path is constant-time — 50k
    # calls in well under half a second even on a loaded CI host
    t0 = time.perf_counter()
    ev = srv.telemetry.tracer.event
    for i in range(50_000):
        ev("enqueue", rid=i, step=i)
    assert time.perf_counter() - t0 < 0.5
    assert srv.telemetry.tracer.records() == []


# ---------------------------------------------------------------------------
# chaos: faults land in the trace; the acceptance gate
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_fault_events_land_in_trace_at_injected_steps(eng):
    """Every fault the seeded injector fires appears in the trace with
    its exact (site, kind, visit) identity, in firing order — the chaos
    run replays as one timeline."""
    prompts = prompts_of((5, 9, 12, 3))
    chaos = [Fault("serving.prefill", "device_error", step=1),
             Fault("serving.decode", "device_error", step=2),
             Fault("engine.decode", "device_error", step=4),
             Fault("serving.decode", "slow", step=6, param=0.005),
             Fault("cache.ensure", "cache_exhausted", step=5)]
    with faults_lib.injected(*chaos, seed=0) as inj:
        tel = Telemetry(sample_every=4)
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, max_retries=3,
                            retry_backoff_s=0.001, telemetry=tel)
        srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)
                 for i, p in enumerate(prompts)])
    assert inj.fired                              # the chaos happened
    traced = [(r[5]["site"], r[5]["kind"], r[5]["visit"])
              for r in tel.tracer.records() if r[1] == "fault"]
    assert traced == inj.fired
    # each traced fault fired at its spec's visit window
    by_spec = {(f.site, f.kind): f for f in chaos}
    for site, kind, visit in traced:
        f = by_spec[(site, kind)]
        assert f.step <= visit < f.step + f.count
    # fault records carry the scheduler step and it never runs backwards
    steps = [r[3] for r in tel.tracer.records() if r[1] == "fault"]
    assert all(s >= 0 for s in steps) and steps == sorted(steps)


@pytest.mark.slow
def test_chaos_acceptance_trace_prometheus_parity_zero_recompiles(
        eng, tmp_path):
    """The ISSUE acceptance gate: under the seeded chaos scenario with
    telemetry ON, the Perfetto + Prometheus exports reconstruct every
    request lifecycle and populate the TTFT/TPOT histograms, injected
    faults sit at their exact visits — while CompileWatch sees ZERO
    steady-state recompiles and tokens stay bit-identical to the
    telemetry-OFF run."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    prompts = prompts_of((5, 9, 12, 3))
    chaos = [Fault("serving.decode", "device_error", step=2),
             Fault("serving.decode", "slow", step=6, param=0.002),
             Fault("cache.ensure", "cache_exhausted", step=5)]

    def drive(telemetry):
        with faults_lib.injected(*chaos, seed=0) as inj:
            srv = ServingEngine(eng, num_slots=2, block_size=4,
                                num_blocks=24, prefill_chunk=8,
                                max_retries=3, retry_backoff_s=0.001,
                                telemetry=telemetry)
            out = srv.run([ServeRequest(rid=i, prompt=p.copy(),
                                        max_new_tokens=6)
                           for i, p in enumerate(prompts)])
        return srv, out, list(inj.fired)

    _, out_off, fired_off = drive(False)          # warmup + baseline
    tel = Telemetry(sample_every=2)
    watch = CompileWatch(max_compiles=0, label="serving+telemetry")
    watch.wrap(eng._prefill_slot)
    watch.wrap(eng._decode_slots)
    with watch:                                   # raises on any compile
        srv, out_on, fired_on = drive(tel)
    # bit-identical tokens, identical fault timeline
    assert sorted(out_on) == sorted(out_off)
    for rid in out_off:
        np.testing.assert_array_equal(out_on[rid], out_off[rid])
    assert fired_on == fired_off
    # Prometheus snapshot: populated latency histograms + live counters
    prom = tel.to_prometheus()
    assert f"serving_completed {srv.stats['completed']}" in prom
    assert tel.registry.histogram("serving_ttft").count == 4
    assert tel.registry.histogram("serving_tpot").count > 0
    assert "serving_ttft_bucket" in prom and "serving_tpot_sum" in prom
    # Perfetto export: trace_analyze reconstructs every lifecycle
    path = tel.export_trace(str(tmp_path / "chaos_trace.json"))
    summary = analyze_serving_trace(path, quiet=True)
    assert set(summary["requests"]) == {"0", "1", "2", "3"}
    for rid, r in summary["requests"].items():
        assert r["spans"][0] == "queued"
        assert "prefill" in r["spans"] and "decode" in r["spans"]
        assert r["state"] == "done"
    assert [(f["site"], f["kind"], f["visit"]) for f in summary["faults"]] \
        == fired_on
    # the sampled step breakdown made it into the export too
    assert {"admission", "prefill", "decode", "bookkeeping"} \
        <= set(summary["phase_us"])
