"""TP checkpoint reshard loader tests (ref: the reference has no unit
tests for state_dict_factory; semantics are verified here against
round-trip identities: split∘merge == identity, merge(mp=1) rebuilds
the full tensor)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deepspeed_tpu.checkpoint import SDLoaderFactory, constants
from deepspeed_tpu.runtime.state_dict_factory import MegatronSDLoader

H = 16
HEADS = 4

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_rank_sd(rng, mp, rank, ckpt_version=2.0):
    """One Megatron-style TP shard: qkv [3h/mp, h], dense [h, h/mp],
    h_to_4h [4h/mp, h], 4h_to_h [h, 4h/mp]."""
    pref = "transformer.layers.0"
    module = {
        f"{pref}.attention.query_key_value.weight":
            rng.standard_normal((3 * H // mp, H)).astype(np.float32),
        f"{pref}.attention.query_key_value.bias":
            rng.standard_normal((3 * H // mp,)).astype(np.float32),
        f"{pref}.attention.dense.weight":
            rng.standard_normal((H, H // mp)).astype(np.float32),
        f"{pref}.attention.dense.bias":
            rng.standard_normal((H,)).astype(np.float32),
        f"{pref}.mlp.dense_h_to_4h.weight":
            rng.standard_normal((4 * H // mp, H)).astype(np.float32),
        f"{pref}.mlp.dense_h_to_4h.bias":
            rng.standard_normal((4 * H // mp,)).astype(np.float32),
        f"{pref}.mlp.dense_4h_to_h.weight":
            rng.standard_normal((H, 4 * H // mp)).astype(np.float32),
        f"{pref}.mlp.dense_4h_to_h.bias":
            rng.standard_normal((H,)).astype(np.float32),
        f"{pref}.input_layernorm.weight":
            np.ones((H,), np.float32),
        "word_embeddings.weight":
            rng.standard_normal((32 // mp, H)).astype(np.float32),
    }
    return {"module": module, "checkpoint_version": ckpt_version}


def _save_shards(tmp_path, mp, seed=0, ckpt_version=2.0, fmt="pt"):
    rng = np.random.default_rng(seed)
    paths = []
    for r in range(mp):
        sd = _make_rank_sd(rng, mp, r, ckpt_version)
        p = str(tmp_path / f"mp_rank_{r:02d}_model_states.{fmt}")
        if fmt == "pt":
            import torch
            torch.save({"module": {k: torch.from_numpy(v) for k, v in
                                   sd["module"].items()},
                        "checkpoint_version": ckpt_version}, p)
        else:
            np.savez(p, __sd__=np.asarray(sd, dtype=object))
        paths.append(p)
    return paths


def test_direct_load(tmp_path):
    paths = _save_shards(tmp_path, mp=2)
    loader = SDLoaderFactory.get_sd_loader(paths, "Megatron", version=2.0)
    load_path, sd, (scales, merge_count) = loader.load(
        mp_world_size=2, mp_rank=1)
    assert load_path == paths[1]
    assert merge_count == 1 and scales is None
    assert sd["module"][
        "transformer.layers.0.attention.dense.weight"].shape == (H, H // 2)


def test_merge_to_mp1(tmp_path):
    paths = _save_shards(tmp_path, mp=2)
    loader = SDLoaderFactory.get_sd_loader(paths, "Megatron", version=2.0)
    _, sd, (_, merge_count) = loader.load(mp_world_size=1, mp_rank=0)
    assert merge_count == 2
    mod = sd["module"]
    p = "transformer.layers.0"
    assert mod[f"{p}.attention.query_key_value.weight"].shape == (3 * H, H)
    assert mod[f"{p}.attention.dense.weight"].shape == (H, H)
    assert mod[f"{p}.mlp.dense_h_to_4h.weight"].shape == (4 * H, H)
    assert mod[f"{p}.mlp.dense_4h_to_h.weight"].shape == (H, 4 * H)
    assert mod["word_embeddings.weight"].shape == (32, H)
    # replicated tensors come from rank 0
    np.testing.assert_allclose(mod[f"{p}.input_layernorm.weight"], 1.0)


def test_split_then_merge_roundtrip(tmp_path):
    """split(1→2) then merge(2→1) must reproduce the original weights."""
    paths = _save_shards(tmp_path, mp=1)
    loader = SDLoaderFactory.get_sd_loader(paths, "Megatron", version=2.0)
    orig = loader.load(mp_world_size=1, mp_rank=0)[1]["module"]

    import torch
    halves = []
    for r in range(2):
        _, sd, _ = loader.load(mp_world_size=2, mp_rank=r)
        p2 = str(tmp_path / f"split_{r}.pt")
        torch.save({"module": {k: torch.from_numpy(np.asarray(v))
                               for k, v in sd["module"].items()},
                    "checkpoint_version": 2.0}, p2)
        halves.append(p2)

    loader2 = SDLoaderFactory.get_sd_loader(halves, "Megatron", version=2.0)
    merged = loader2.load(mp_world_size=1, mp_rank=0)[1]["module"]
    for k in orig:
        np.testing.assert_allclose(merged[k], orig[k], err_msg=k)


def test_qkv_version0_interleaved(tmp_path):
    """v0 layout [(3*np*hn), h]: merge must interleave-regroup, so it
    differs from plain concat but roundtrips with split."""
    paths = _save_shards(tmp_path, mp=2, ckpt_version=0)
    loader = SDLoaderFactory.get_sd_loader(paths, "Megatron", version=0)
    _, merged_sd, _ = loader.load(mp_world_size=1, mp_rank=0)
    key = "transformer.layers.0.attention.query_key_value.weight"
    merged = merged_sd["module"][key]
    assert merged.shape == (3 * H, H)
    # roundtrip: splitting the merged tensor back to 2 ranks reproduces
    # each rank's original shard
    rank_shards = [
        np.asarray(loader.load(mp_world_size=2, mp_rank=r)[1]["module"][key])
        for r in range(2)]
    m = MegatronSDLoader([paths[0]], version=0)
    for r in range(2):
        back = m.split_query_key_value(merged, 2, r, 0)
        # split-of-merge equals the original rank shard
        orig = _load_rank_qkv(paths[r])
        np.testing.assert_allclose(back, orig)
    del rank_shards


def _load_rank_qkv(path):
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=False)
    return sd["module"][
        "transformer.layers.0.attention.query_key_value.weight"].numpy()


def test_quantized_load(tmp_path):
    paths = _save_shards(tmp_path, mp=2)
    loader = SDLoaderFactory.get_sd_loader(paths, "Megatron", version=2.0)
    _, sd, (scales, _) = loader.load(mp_world_size=1, mp_rank=0,
                                     quantize=True, quantize_bits=8,
                                     quantize_groups=4)
    mod = sd["module"]
    key = "transformer.layers.0.attention.dense.weight"
    assert mod[key].dtype == np.int8
    assert scales is not None and scales.ndim == 3


def test_loader_json_and_validation(tmp_path):
    paths = _save_shards(tmp_path, mp=2)
    cfg = {"type": "Megatron", "checkpoints": paths, "version": 2.0}
    jpath = tmp_path / "ckpt.json"
    jpath.write_text(json.dumps(cfg))
    loader = SDLoaderFactory.get_sd_loader_json(str(jpath))
    assert isinstance(loader, MegatronSDLoader)
    with pytest.raises(ValueError):
        SDLoaderFactory.get_sd_loader(paths, sd_type="HF")
    with pytest.raises(AssertionError):
        SDLoaderFactory.get_sd_loader(["/nonexistent.pt"], "Megatron")


def test_checkpoint_constants():
    assert constants.OPTIMIZER_STATE_DICT == "optimizer_state_dict"
    assert constants.ZERO_STAGE == "zero_stage"
    assert constants.DS_VERSION == "ds_version"


def test_zero_to_fp32_cli(tmp_path, devices):
    """Engine save → offline consolidation CLI → full fp32 npz
    (ref: deepspeed/utils/zero_to_fp32.py workflow)."""
    import deepspeed_tpu
    from tests.simple_model import random_batch, simple_model_loss, \
        simple_model_params
    params = simple_model_params(hidden_dim=16)
    cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
           "zero_optimization": {"stage": 3, "stage3_min_shard_size": 1},
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    engine.train_batch(random_batch(8, 16))
    ckpt_dir = tmp_path / "ckpt"
    engine.save_checkpoint(str(ckpt_dir))

    out = tmp_path / "fp32.npz"
    from deepspeed_tpu.cli import zero_to_fp32_main
    zero_to_fp32_main([str(ckpt_dir), str(out)])
    with np.load(str(out)) as z:
        assert "layer_0.kernel" in z.files
        assert z["layer_0.kernel"].shape == (16, 16)
        assert z["layer_0.kernel"].dtype == np.float32
